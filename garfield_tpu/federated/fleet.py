"""Simulated client fleets: autoscaled driver processes for fed rounds.

A million-client round does not mean a million OS processes: the fleet
is DRIVEN by a handful of client processes, each simulating a block of
cohort members per round (generating their gradients and publishing one
wave frame per shard — the batched form every real FL driver converges
to). This module owns the process lifecycle and composes it with the
load controller of ``utils.autoscale``: the round engine reports its
round wall time, the ``AutoscaleController`` decides spawn/retire
against the target round rate, and the fleet spawns a fresh client
driver (re-targeting this process's own CLI at ``client:K``, the
``worker_command`` pattern) or retires one.

Retirement is abrupt by design: a fed CLIENT is stateless between
rounds (it re-reads the broadcast model every round and carries no
quorum obligations — unlike a cluster WORKER, whose retirement is a
clean stop-sentinel teardown, utils/autoscale docstring), so terminate
+ exchange watcher teardown is the whole protocol; the PS's next quorum
simply prices the smaller fleet. Each action lands as the existing
``autoscale`` telemetry event (schema v6) so the fed plane reuses the
spawns/retires digest and Prometheus counters unchanged.
"""

import subprocess

from ..telemetry import hub as tele_hub
from ..utils import autoscale as autoscale_lib

__all__ = ["ClientFleet", "client_command"]


def client_command(cindex, argv=None, main_module=None):
    """This process's CLI re-targeted at the ``client:cindex`` role —
    ``utils.autoscale.worker_command`` with the fed client role (the
    PS-only autoscale knobs are stripped the same way)."""
    return autoscale_lib.worker_command(
        cindex, argv=argv, main_module=main_module, role="client"
    )


class ClientFleet:
    """Elastic pool of simulated client driver processes.

    ``command_for(index)`` builds a child's argv (usually via
    ``client_command``); ``cfg`` is the ``AutoscaleConfig`` contract.
    The fleet spawns the lowest free index (stable rank reuse — a
    respawned index rejoins the exchange through the same host slot)
    and retires the highest live one.
    """

    def __init__(self, command_for, cfg, *, env=None, on_retire=None):
        self.command_for = command_for
        self.controller = autoscale_lib.AutoscaleController(cfg)
        self.cfg = cfg
        self.spawns = 0
        self.retires = 0
        self._env = env
        self._on_retire = on_retire
        self._procs = {}

    # -- membership ---------------------------------------------------------

    def active(self):
        return sorted(
            k for k, p in self._procs.items() if p.poll() is None
        )

    def spawn(self, index):
        if index in self._procs and self._procs[index].poll() is None:
            return self._procs[index]
        p = subprocess.Popen(self.command_for(index), env=self._env)
        self._procs[index] = p
        self.spawns += 1
        tele_hub.emit_event(
            "autoscale", action="spawn", rank=int(index),
            active=len(self.active()), rate=self.controller.rate(),
            target=self.controller.target or None,
        )
        return p

    def spawn_initial(self, count):
        for k in range(count):
            self.spawn(k)
        return self.active()

    def retire(self, index=None):
        live = self.active()
        if not live:
            return None
        index = live[-1] if index is None else index
        p = self._procs.get(index)
        if p is None:
            return None
        if self._on_retire is not None:
            try:
                self._on_retire(index)
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        if p.poll() is None:
            p.terminate()
            try:
                # Block until the process is actually gone: ``active()``
                # is poll()-based, and a PS that counts a half-dead
                # driver into its next quorum waits the full round
                # timeout for a frame that will never come.
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        self.retires += 1
        tele_hub.emit_event(
            "autoscale", action="retire", rank=int(index),
            active=len(self.active()), rate=self.controller.rate(),
            target=self.controller.target or None,
        )
        return index

    # -- the control loop ---------------------------------------------------

    def observe(self, round_s, *, quorum_margin=0):
        """Fold one round into the controller; act on its verdict.
        Returns ``(action, index)``: +1/-1/0 (the action TAKEN, not just
        advised) and the spawned/retired driver index (None on 0) — the
        caller must drop a retired index from its own round membership
        immediately, before the next quorum prices it in."""
        action = self.controller.observe(
            round_s, active=len(self.active()),
            quorum_margin=quorum_margin,
        )
        if action > 0:
            live = set(self.active())
            free = 0
            while free in live:
                free += 1
            if free >= self.cfg.max_workers:
                # Refused spawn: every driver index is occupied. The
                # controller already charged its cooldown for the
                # advice — rescind it, or the refusal silences scaling
                # for a full cooldown + window refill with the fleet
                # unchanged (the satellite-2 accounting bug).
                self.controller.rescind()
                return 0, None
            self.spawn(free)
            return action, free
        if action < 0:
            return action, self.retire()
        return 0, None

    # -- teardown -----------------------------------------------------------

    def stop_all(self, timeout=30):
        for k, p in list(self._procs.items()):
            if p.poll() is None:
                p.terminate()
        for p in self._procs.values():
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop_all()

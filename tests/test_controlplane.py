"""Control plane (garfield_tpu/controlplane/, DESIGN.md §22).

Fast tier-1 coverage: the membership-view codec's loud-reject surface
(truncation at every depth, host-length lies, CRC/epoch tamper,
partition invariants), the directory's strict epoch monotonicity (the
replay ban), heartbeat failure detection (in-probe retries, once-only
death, revive, the real-TCP probe), the failover handoff's API contract
(checkpoint substrate required, suspicion carried forward max-merge,
the ErrorFeedback zero-rebuild pin), the shard autoscaler's
rescind-on-refusal accounting, the env knobs, the schema-v13
membership/soak_bench validators, and a ≤30 s soak smoke (rolling
restart + partitions + churn at toy scale). The full-scale soak (the
committed SOAKBENCH_r01 shape) is slow-marked. The engine-level
failover bitwise-determinism pin lives in tests/test_federated.py
beside the other trajectory anchors.
"""

import json
import os
import socket

import numpy as np
import pytest

from garfield_tpu import controlplane as cp
from garfield_tpu import federated as fed
from garfield_tpu.apps.benchmarks import soak_bench
from garfield_tpu.controlplane import membership as ms
from garfield_tpu.telemetry import exporters, hub as tele_hub
from garfield_tpu.utils import wire

RNG = np.random.default_rng(20260807)


def _view(epoch=3, d=100, shards=4, host="127.0.0.1", port0=9000):
    spec = fed.plan_shards(d, shards)
    return cp.MembershipView(epoch, d, [
        cp.Seat(s, host, port0 + s, lo, hi)
        for s, (lo, hi) in enumerate(spec.spans)
    ])


# ---------------------------------------------------------------------------
# membership views


class TestSeat:
    def test_validation(self):
        cp.Seat(0, "host.example", 80, 0, 10)  # valid
        with pytest.raises(cp.ViewError, match="port"):
            cp.Seat(0, "h", 70000, 0, 10)
        with pytest.raises(cp.ViewError, match="empty or negative"):
            cp.Seat(0, "h", 80, 10, 10)
        with pytest.raises(cp.ViewError, match="length field"):
            cp.Seat(0, "x" * 300, 80, 0, 10)
        with pytest.raises(ValueError):
            cp.Seat(99, "h", 80, 0, 10)  # past the wire nibble


class TestMembershipView:
    def test_partition_invariants(self):
        v = _view()
        assert v.num_shards == 4 and v.epoch == 3
        spec = fed.plan_shards(100, 4)
        # gap
        seats = [cp.Seat(s, "h", 1, lo, hi)
                 for s, (lo, hi) in enumerate(spec.spans)]
        bad = seats[:1] + [cp.Seat(1, "h", 1, 30, 50)] + seats[2:]
        with pytest.raises(cp.ViewError, match="contiguously"):
            cp.MembershipView(1, 100, bad)
        # wrong keying
        with pytest.raises(cp.ViewError, match="keyed"):
            cp.MembershipView(1, 100, seats[::-1])
        # coverage short of d
        with pytest.raises(cp.ViewError, match="claims"):
            cp.MembershipView(1, 101, seats)
        # epoch must fit the wire header's u32 stamp
        with pytest.raises(ValueError):
            cp.MembershipView(wire.MAX_EPOCH + 1, 100, seats)
        with pytest.raises(cp.ViewError, match="1..16"):
            cp.MembershipView(1, 100, [])

    def test_spec_canonical_partition(self):
        v = _view(d=101, shards=4)
        spec = v.spec()
        assert spec.d == 101 and spec.num_shards == 4
        # A non-balanced tiling is a valid VIEW but not an engine spec.
        odd = cp.MembershipView(1, 100, [
            cp.Seat(0, "h", 1, 0, 90), cp.Seat(1, "h", 1, 90, 100)
        ])
        with pytest.raises(cp.ViewError, match="balanced"):
            odd.spec()

    def test_roundtrip_and_equality(self):
        v = _view(epoch=7, d=257, shards=5, host="ps-3.cluster.local")
        buf = v.encode()
        out = cp.MembershipView.decode(buf)
        assert out == v and out.seats[2] == v.seats[2]
        assert cp.MembershipView.decode(bytearray(buf)) == v

    def test_decode_rejects_every_malformation(self):
        buf = _view().encode()
        with pytest.raises(cp.ViewError, match="header"):
            cp.MembershipView.decode(buf[:10])
        with pytest.raises(cp.ViewError, match="magic"):
            cp.MembershipView.decode(b"XX" + buf[2:])
        with pytest.raises(cp.ViewError, match="version"):
            cp.MembershipView.decode(buf[:2] + b"\x09" + buf[3:])
        with pytest.raises(cp.ViewError, match="CRC"):
            cp.MembershipView.decode(buf[:-1] + bytes([buf[-1] ^ 1]))
        with pytest.raises(cp.ViewError, match="CRC|truncated"):
            cp.MembershipView.decode(buf[:-3])  # truncated seat table
        with pytest.raises(cp.ViewError, match="CRC|trailing"):
            cp.MembershipView.decode(buf + b"\x00")

    def test_epoch_restamp_is_crc_mismatch(self):
        # The CRC is seeded with the epoch bytes (the wire v2
        # construction): a relay rewriting the header epoch without
        # re-authoring the record fails the CRC, attributably.
        buf = bytearray(_view(epoch=3).encode())
        off = 4  # magic(2) + ver(1) + num_seats(1); epoch is !I next
        buf[off:off + 4] = (9).to_bytes(4, "big")
        with pytest.raises(cp.ViewError, match="CRC"):
            cp.MembershipView.decode(bytes(buf))

    def test_host_length_lie(self):
        v = cp.MembershipView(1, 10, [cp.Seat(0, "abcdef", 1, 0, 10)])
        buf = bytearray(v.encode())
        # The seat's host_len byte sits right before the host bytes.
        idx = bytes(buf).rindex(b"abcdef") - 1
        assert buf[idx] == 6
        buf[idx] = 200  # claims 200 host bytes; only 6 follow
        with pytest.raises(cp.ViewError, match="CRC|host"):
            cp.MembershipView.decode(bytes(buf))

    def test_for_engine(self):
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.zeros(40, np.float32), 4, smp,
                                 epoch=5)
        v = cp.MembershipView.for_engine(eng, ports=[1, 2, 3, 4])
        assert v.epoch == 5 and v.d == 40 and v.num_shards == 4
        assert [s.port for s in v.seats] == [1, 2, 3, 4]
        assert tuple(v.spec().spans) == tuple(eng.spec.spans)
        with pytest.raises(cp.ViewError, match="ports"):
            cp.MembershipView.for_engine(eng, ports=[1])


class TestMembershipDirectory:
    def test_strictly_newer_epochs_only(self):
        d = cp.MembershipDirectory(_view(epoch=3))
        assert d.epoch == 3 and d.installs == 1
        d.install(_view(epoch=4))
        assert d.epoch == 4
        # Replay of the superseded view AND a duplicate of the current
        # one are both the stale-view ban, counted as evidence.
        for stale in (3, 4):
            with pytest.raises(cp.StaleViewError, match="attributable"):
                d.install(_view(epoch=stale))
        assert d.rejects == 2 and "epoch 4" in d.last_reject
        assert d.epoch == 4  # unchanged by the rejects

    def test_install_frame_and_malformed_not_counted_stale(self):
        d = cp.MembershipDirectory()
        assert d.epoch is None
        d.install_frame(_view(epoch=2).encode())
        assert d.epoch == 2
        with pytest.raises(cp.ViewError):
            d.install_frame(b"garbage-bytes")
        assert d.rejects == 0  # malformed != stale: no admissible epoch
        with pytest.raises(TypeError):
            d.install("not a view")


# ---------------------------------------------------------------------------
# heartbeat failure detection


class TestHeartbeatMonitor:
    def test_transient_loss_survives_in_probe_retries(self):
        # Two consecutive probe failures, then success: with retries=3
        # the target never dies — one dropped SYN is not a failover.
        fails = {"left": 2}

        def probe(key):
            if fails["left"] > 0:
                fails["left"] -= 1
                return False
            return True

        mon = cp.HeartbeatMonitor({"a": ("a",)}, probe=probe,
                                  interval_s=0.001, retries=3,
                                  backoff_s=0)
        assert mon.poll() == []
        assert mon.down == set() and mon.probes == 3

    def test_death_fires_once_and_revive_rearms(self):
        alive = {"a": True, "b": True}
        deaths = []
        mon = cp.HeartbeatMonitor(
            {k: (k,) for k in alive}, probe=lambda k: alive[k],
            interval_s=0.001, retries=2, backoff_s=0,
            on_down=deaths.append,
        )
        assert mon.run_once() == []
        alive["b"] = False
        assert mon.poll() == ["b"] and deaths == ["b"]
        assert mon.poll() == []  # a dead target is not re-declared
        mon.revive("b", target=("b",))
        alive["b"] = True
        assert mon.poll() == [] and mon.down == set()

    def test_raising_probe_is_a_failed_probe(self):
        def probe(key):
            raise OSError("probe transport died")

        mon = cp.HeartbeatMonitor({"a": ("a",)}, probe=probe,
                                  interval_s=0.001, retries=1,
                                  backoff_s=0)
        assert mon.poll() == ["a"]

    def test_retries_validated(self):
        with pytest.raises(ValueError, match="retries"):
            cp.HeartbeatMonitor({}, retries=0, interval_s=0.001)

    def test_tcp_probe_real_socket(self):
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            host, port = srv.getsockname()
            assert cp.tcp_probe(host, port, timeout_s=1.0)
        finally:
            srv.close()
        # The port is closed now: connection refused, not a hang.
        assert not cp.tcp_probe(host, port, timeout_s=0.5)


class TestEnvKnobs:
    def test_heartbeat_interval(self, monkeypatch):
        monkeypatch.delenv("GARFIELD_HEARTBEAT_MS", raising=False)
        assert cp.heartbeat_interval_s() == pytest.approx(0.1)
        monkeypatch.setenv("GARFIELD_HEARTBEAT_MS", "250")
        assert cp.heartbeat_interval_s() == pytest.approx(0.25)
        monkeypatch.setenv("GARFIELD_HEARTBEAT_MS", "nope")
        with pytest.raises(ValueError, match="GARFIELD_HEARTBEAT_MS"):
            cp.heartbeat_interval_s()
        monkeypatch.setenv("GARFIELD_HEARTBEAT_MS", "0")
        with pytest.raises(ValueError):
            cp.heartbeat_interval_s()

    def test_standby_shards(self, monkeypatch):
        monkeypatch.delenv("GARFIELD_STANDBY_SHARDS", raising=False)
        assert cp.standby_shards() == 1
        monkeypatch.setenv("GARFIELD_STANDBY_SHARDS", "3")
        assert cp.standby_shards() == 3
        monkeypatch.setenv("GARFIELD_STANDBY_SHARDS", "-1")
        with pytest.raises(ValueError):
            cp.standby_shards()

    def test_soak_env_defaults(self, monkeypatch):
        monkeypatch.setenv("GARFIELD_SOAK_ROUNDS", "9")
        monkeypatch.setenv("GARFIELD_SOAK_COHORT", "24")
        monkeypatch.setenv("GARFIELD_SOAK_D", "128")
        monkeypatch.setenv("GARFIELD_SOAK_SHARDS", "2")
        assert soak_bench._env_int("GARFIELD_SOAK_ROUNDS", 60) == 9
        assert soak_bench._env_int("GARFIELD_SOAK_COHORT", 64) == 24
        assert soak_bench._env_int("GARFIELD_SOAK_D", 2048) == 128
        assert soak_bench._env_int("GARFIELD_SOAK_SHARDS", 4) == 2


# ---------------------------------------------------------------------------
# failover handoff


class TestFailover:
    def test_requires_checkpoint_substrate(self):
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.zeros(32, np.float32), 2, smp,
                                 epoch=1)
        with pytest.raises(RuntimeError, match="checkpoint_dir"):
            cp.promote_standby(eng, 0)

    def test_no_complete_checkpoint_is_loud(self, tmp_path):
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.zeros(32, np.float32), 2, smp,
                                 epoch=1, checkpoint_dir=str(tmp_path))
        with pytest.raises(FileNotFoundError, match="complete"):
            cp.promote_standby(eng, 0)

    def test_handoff_restores_span_suspicion_and_bumps_epoch(
            self, tmp_path):
        hub = tele_hub.MetricsHub()
        prev = tele_hub.install(hub)
        try:
            hub.absorb_client_suspicion({7: (3.0, 2.0)})
            smp = fed.CohortSampler(16, 16, seed=4, byz_frac=0.05)
            eng = fed.FedRoundEngine(
                RNG.normal(size=64).astype(np.float32), 2, smp,
                epoch=1, checkpoint_dir=str(tmp_path),
            )
            eng.begin_round()
            eng.ingest_rows(RNG.normal(size=(16, 64)).astype(np.float32))
            eng.finish_round()  # writes the round-0 checkpoint
            saved_span = eng.model[eng.spec.spans[1][0]:
                                   eng.spec.spans[1][1]].copy()
            # Dirty shard 1's span in memory (the half-updated state a
            # mid-round death leaves behind), then wipe the hub's
            # suspicion the way a standby's fresh process would.
            eng.model[eng.spec.spans[1][0]:eng.spec.spans[1][1]] = -1.0
            tele_hub.install(tele_hub.MetricsHub())
            srv, rerun = cp.promote_standby(eng, 1)
            assert rerun == 1 and eng.epoch == 2 and srv.epoch == 2
            assert np.array_equal(
                eng.model[eng.spec.spans[1][0]:eng.spec.spans[1][1]],
                saved_span,
            )
            # The checkpointed suspicion rode the control record into
            # the standby's hub — the crash cannot launder history.
            snap = tele_hub.current().client_suspicion_snapshot()
            assert snap.get(7, (0.0, 0.0))[1] >= 2.0
            # The standby serves exactly the interrupted round.
            with pytest.raises(RuntimeError, match="refusing loudly"):
                srv.begin_round(5, 16, eng.shards[0]._red.f)
        finally:
            tele_hub.install(prev)

    def test_error_feedback_zero_rebuild_pin(self):
        # The recorded PR 14 decision, pinned: a restart/handoff does
        # NOT restore wire ErrorFeedback residuals — a fresh instance
        # starts at zero and the handoff module says so as data.
        assert cp.EF_RESIDUAL_RESTORED is False
        ef = wire.ErrorFeedback()
        v = RNG.normal(size=64).astype(np.float32)
        ef.update("grad", v, np.zeros_like(v))
        assert ef.residual_norm("grad") > 0.0
        # A rebuilt (post-restart / post-handoff) accumulator is zero.
        assert wire.ErrorFeedback().residual_norm("grad") == 0.0
        assert wire.ErrorFeedback().total_norm() == 0.0


# ---------------------------------------------------------------------------
# shard autoscaling


class TestShardAutoscaler:
    def test_refused_split_rescinds(self):
        # d=8 at S=8: a split to 9 is impossible (more shards than
        # parameters) — the engine refuses, the controller's accounting
        # must show NOTHING: no action count, no consumed cooldown.
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.ones(8, np.float32), 8, smp, epoch=1)
        sc = cp.ShardAutoscaler(eng, target_rate=100.0, window=2,
                                cooldown=0)
        deltas = [sc.observe(1.0) for _ in range(4)]
        assert all(d == 0 for d in deltas)
        assert sc.refusals >= 1 and sc.controller.actions == 0
        assert eng.spec.num_shards == 8 and eng.epoch == 1

    def test_split_and_merge_bump_epoch(self):
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.ones(64, np.float32), 2, smp,
                                 epoch=1)
        sc = cp.ShardAutoscaler(eng, target_rate=100.0, window=2,
                                cooldown=0, max_shards=4)
        while eng.spec.num_shards < 4:
            sc.observe(1.0)  # sustained pressure: split toward the cap
        assert sc.splits == 2 and eng.epoch == 3
        sc2 = cp.ShardAutoscaler(eng, target_rate=1.0, window=2,
                                 cooldown=0)
        deltas = [sc2.observe(0.001) for _ in range(4)]
        assert -1 in deltas and eng.spec.num_shards < 4

    def test_unhealthy_round_vetoes_merge(self):
        smp = fed.CohortSampler(64, 8, seed=0)
        eng = fed.FedRoundEngine(np.ones(64, np.float32), 4, smp,
                                 epoch=1)
        sc = cp.ShardAutoscaler(eng, target_rate=1.0, window=3,
                                cooldown=0)
        # Fast rounds (merge territory) but one carried a failover:
        # shrinking into a wobble is forbidden for a full window.
        for i in range(3):
            assert sc.observe(0.001, healthy=(i != 1)) == 0
        assert eng.spec.num_shards == 4


# ---------------------------------------------------------------------------
# schema v13


class TestSchemaV13:
    def test_membership_event_validates(self):
        rec = exporters.make_record(
            "event", event="membership", epoch=4, action="failover",
            shard=1, num_shards=4, step=12,
        )
        exporters.validate_record(rec)
        rec_pre = exporters.make_record(
            "event", event="membership", epoch=None, action="split",
            shard=None, num_shards=2, step=0,
        )
        exporters.validate_record(rec_pre)
        for bad in (
            dict(rec, action=""),
            dict(rec, epoch=-1),
            dict(rec, num_shards=0),
            dict(rec, shard=-2),
        ):
            with pytest.raises(ValueError, match="membership"):
                exporters.validate_record(bad)

    def test_soak_bench_kind_validates(self):
        rec = exporters.make_record(
            "soak_bench", check="rolling_restart", rounds=60, d=2048,
            shards=4, cohort=64, population=256, p50_s=0.01,
            p95_s=0.02, p99_s=0.03, mean_s=0.012, wall_s=1.5,
            failovers=6, partitions=0, stale_rejects=0, epoch_final=7,
            kill_cost_rounds=0.4, bitwise_equal=True,
        )
        exporters.validate_record(rec)
        for bad in (
            dict(rec, check=""),
            dict(rec, rounds=0),
            dict(rec, failovers=-1),
            dict(rec, p99_s="slow"),
            dict(rec, bitwise_equal=1),
        ):
            with pytest.raises(ValueError, match="soak_bench"):
                exporters.validate_record(bad)
        assert exporters.SCHEMA_VERSION >= 13


# ---------------------------------------------------------------------------
# the soak harness


def _soak_args(tmp_path, rounds):
    return [
        "--rounds", str(rounds), "--cohort", "16", "--d", "256",
        "--shards", "2", "--kill_every", "2", "--part_every", "2",
        "--churn_max_shards", "3",
        "--json", str(tmp_path / "SOAK.json"),
    ]


class TestSoakBench:
    def test_smoke_all_scenarios(self, tmp_path):
        """≤30 s: every scenario at toy scale, with kills and
        partitions actually exercised, the artifact twin written and
        schema-v13 valid."""
        rows = soak_bench.main(_soak_args(tmp_path, 4))
        by = {r["check"]: r for r in rows}
        assert set(by) == {"steady", "rolling_restart", "partition",
                           "churn"}
        rr = by["rolling_restart"]
        assert rr["failovers"] >= 1
        assert rr["bitwise_equal"] is True
        # The handoff contract, measured: a mid-round kill costs at
        # most one extra round of latency.
        assert rr["kill_cost_rounds"] is not None
        assert rr["kill_cost_rounds"] <= 1.0
        assert rr["epoch_final"] == 1 + rr["failovers"]
        pt = by["partition"]
        assert pt["stale_rejects"] == 3 * pt["partitions"] > 0
        for row in rows:
            assert row["rounds"] == 4
            assert row["p50_s"] <= row["p95_s"] <= row["p99_s"]
        assert exporters.validate_jsonl(str(tmp_path / "SOAK.jsonl")) == 4
        with open(tmp_path / "SOAK.json") as fp:
            assert len(json.load(fp)) == 4

    @pytest.mark.slow
    def test_full_scale_soak(self, tmp_path):
        """The committed SOAKBENCH_r01 shape: default knobs, 4 x 60
        sustained rounds under rolling restarts, partitions and
        churn."""
        rows = soak_bench.main([
            "--json", str(tmp_path / "SOAKBENCH.json"),
        ])
        assert sum(r["rounds"] for r in rows) >= 200
        rr = {r["check"]: r for r in rows}["rolling_restart"]
        assert rr["bitwise_equal"] is True
        assert rr["kill_cost_rounds"] <= 1.0


# ---------------------------------------------------------------------------
# committed artifact pins


class TestCommittedArtifact:
    def test_soakbench_r01_claims(self):
        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "SOAKBENCH_r01.json")
        with open(path) as fp:
            rows = json.load(fp)
        by = {r["check"]: r for r in rows}
        assert set(by) == {"steady", "rolling_restart", "partition",
                           "churn"}
        # The acceptance floor: ≥200 sustained rounds, a measured
        # mid-round kill cost ≤ 1 round, bitwise-identical trajectory
        # through every failover, and every stale injection rejected.
        assert sum(r["rounds"] for r in rows) >= 200
        rr = by["rolling_restart"]
        assert rr["failovers"] >= 5 and rr["bitwise_equal"] is True
        assert rr["kill_cost_rounds"] <= 1.0
        assert by["partition"]["stale_rejects"] \
            == 3 * by["partition"]["partitions"] > 0
        assert by["churn"]["resizes"] >= 1
        for r in rows:
            assert r["p50_s"] <= r["p95_s"] <= r["p99_s"]

"""Microbenchmarks (P21): GAR kernel latency sweeps and collective-transfer
latency, counterparts of pytorch_impl/applications/benchmarks/
{gar_bench,rpc_bench}.py."""

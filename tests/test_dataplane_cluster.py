"""Data-plane defense, end to end (slow, multi-process).

The deployment twin of tests/test_dataplane.py (DESIGN.md §18): a REAL
backdoor-poisoning worker process (``--attack backdoor`` — trigger
stamps + target labels on its own shard, honest gradients of the
poisoned task) against an SSMW PS running ``--defense escalate+data``
(the GAR-side suspicion ladder AND the fingerprint detectors over the
wire frames it decodes), over PeerExchange on localhost.

Registered in conftest._RUN_LAST (multi-process e2e discipline): spawns
subprocess fleets and compiles per process — slow-marked, collects last.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "1.35"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def test_backdoor_worker_vs_dataplane_defending_ps(tmp_path):
    """1 PS (--defense escalate+data) + 6 workers, one a real backdoor
    poisoner: every role exits rc 0, the PS stream carries schema-v9
    ``data_defense`` events and the ``summary.data_defense`` digest,
    and the detector history concentrates its flags on the poisoning
    worker's rank — the wire-frame twin of the in-graph detectors."""
    from garfield_tpu.utils import multihost

    n_w = 6
    byz = n_w - 1
    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _env()
    tele = str(tmp_path / "tele")
    base = [
        sys.executable, "-m", "garfield_tpu.apps.aggregathor",
        "--cluster", cfg_path,
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--fw", "1", "--gar", "krum",
        "--num_iter", "40", "--acc_freq", "20",
        "--opt_args", '{"lr":"0.05"}',
        "--cluster_timeout_ms", "120000",
    ]
    ps = subprocess.Popen(
        base + ["--task", "ps:0", "--defense", "escalate+data",
                "--defense_params", '{"dp_halflife": 4.0}',
                "--suspicion_halflife", "10", "--telemetry", tele],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    workers = []
    for k in range(n_w):
        argv = base + ["--task", f"worker:{k}"]
        if k == byz:
            argv += ["--attack", "backdoor",
                     "--attack_params",
                     '{"source": 0, "target": 1, "poison_frac": 1.0}']
        workers.append(subprocess.Popen(
            argv, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        ))
    try:
        out, _ = ps.communicate(timeout=600)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        for k, w in enumerate(workers):
            w.wait(timeout=180)
            assert w.returncode == 0, f"worker {k} rc {w.returncode}"
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "cluster-ps.telemetry.jsonl"))
    ]
    # Schema-v9 plumbing: data_defense events landed in the stream and
    # every record (the new event shape included) validates.
    dd = [r for r in recs if r.get("event") == "data_defense"]
    assert dd, "PS emitted no data_defense events"
    from garfield_tpu.telemetry import validate_jsonl

    validate_jsonl(os.path.join(tele, "cluster-ps.telemetry.jsonl"))
    summaries = [r for r in recs if r["kind"] == "summary"]
    assert summaries and summaries[-1]["data_defense"] is not None
    assert summaries[-1]["data_defense"]["rounds"] > 0
    # Detector attribution: the poisoning worker's rank collects the
    # most flags, and by the final rounds its composed weight is below
    # every honest rank's.
    flags_by_rank = {}
    for r in dd:
        for rank, fl in zip(r["ranks"], r["flags"]):
            flags_by_rank[rank] = flags_by_rank.get(rank, 0) + int(fl)
    assert flags_by_rank.get(byz, 0) > 0, flags_by_rank
    assert flags_by_rank[byz] == max(flags_by_rank.values()), (
        flags_by_rank
    )
    last = dd[-1]
    w_by_rank = dict(zip(last["ranks"], last["weights"]))
    if byz in w_by_rank:
        assert w_by_rank[byz] <= min(
            v for r, v in w_by_rank.items() if r != byz
        ), w_by_rank

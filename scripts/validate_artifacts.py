"""Schema-check every committed telemetry JSONL artifact.

The BENCH_r05 post-mortem rule, mechanized: a bench capture that drifts
from the telemetry schema must fail LOUDLY at commit time, not parse
half-way in a later analysis session. This walks the repo root for
``*_r*.jsonl`` artifacts (EXCHBENCH_r*, HIERBENCH_r*, ...) plus every
committed fixture stream under ``tests/fixtures/``, and runs
``telemetry.exporters.validate_jsonl`` over each — wired into tier-1 by
``tests/test_trace.py::TestValidateArtifacts`` so schema drift in a
future round fails the suite. Covers every registered record kind,
including the schema-v7 ``defense_bench`` rows (DEFBENCH_r*: the
adaptive-attack / closed-loop-defense accuracy cells) and the v7
event/summary additions (attack_adapt, defense_weights,
defense_escalate, attack_fallback, suspicion_decayed) — and the v8
threat-model-matrix additions (ps_attack_adapt, targeted_eval,
plane-tagged defense events, the DEFBENCH_r02 grid rows with
plane/confusion/asr columns) — and the v9 data-plane-defense additions
(the data_defense event with matched-length scores/flags/weights/ranks
lists, summary.data_defense, the asr_baseline field on targeted_eval
events and DEFBENCH_r03's defense_bench rows with the composed
data/escalate+data defense strings) — and the v10 federated additions
(the ``fed_bench`` kind behind FEDBENCH_r*'s scaling / s1_bitwise /
fleet rows, the ``fed_round`` event with its per-shard digest, the
``cohort`` event's matched-length client_ids/selected lists, and
``summary.federated`` with its client-id-keyed top_clients map) — and
the v11 compression additions (the ``wire`` event's per-scheme byte
breakdown + compression_ratio/ef_residual_norm, ``summary.wire_schemes``,
and EXCHBENCH_r05's ``--robust`` exchange_bench rows with their
cell/matched_accuracy/headroom columns; auto-globbed like every
``*_r*.jsonl``) — and the v12 selection-kernel additions (FEDBENCH_r02's
``fed_bench`` scaling rows with their per-phase ``phases`` p50/p95
attribution — ingest/h2d/fold/selection — and SELBENCH-style
``gar_bench`` rows with grid/impl/wave_buckets/per_bucket_s columns) —
and the v13 control-plane additions (the ``soak_bench`` kind behind
SOAKBENCH_r*'s steady / rolling_restart / partition / churn rows with
their p50/p95/p99 SLO columns and the measured ``kill_cost_rounds``,
plus the ``membership`` event — one epoch bump per failover / split /
merge; both auto-globbed like every ``*_r*.jsonl``) — and the v14
slot-fused-transformer additions (the ``trans_bench`` kind behind
TRANSBENCH_r*'s rows: fused-vs-unrolled A/B latency cells with their
``dw_mode``/``dce_guard``/``per_slot_grad_s``/``speedup`` columns and
the token-backdoor robustness cells with ``asr``/``asr_baseline``/
``accuracy`` per defense; auto-globbed like every ``*_r*.jsonl``) — and
the v15 batched-wire-ingest additions (the ``ingest_batch`` event —
per-bulk-call shard/frames/rejected/bytes with ``rejected <= frames``
and accepted-only byte accounting — plus the ``fed_bench`` kind's
``check="ingest_micro"`` row family behind INGESTBENCH_r*'s
batch-vs-per-frame decode A/B cells and FEDBENCH_r03's scaling rows
with per-phase attribution on every row; both auto-globbed like every
``*_r*.jsonl``).

  python scripts/validate_artifacts.py            # repo root auto-found
  python scripts/validate_artifacts.py /some/repo
"""

import glob
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def find_artifacts(root=None):
    """Sorted list of committed JSONL artifacts under ``root``: the
    ``*_r*.jsonl`` bench captures at the top level and every fixture
    ``*.jsonl`` under tests/fixtures/."""
    root = root or _REPO
    paths = sorted(glob.glob(os.path.join(root, "*_r*.jsonl")))
    paths += sorted(glob.glob(
        os.path.join(root, "tests", "fixtures", "**", "*.jsonl"),
        recursive=True,
    ))
    return paths


def find_json_twins(root=None):
    """The ``*_r*.json`` twins of the JSONL artifacts (EXCHBENCH_r04's
    scaleup/learn rows and friends): not schema-versioned, but a twin
    that fails to parse is the same dark-artifact failure mode."""
    root = root or _REPO
    return sorted(glob.glob(os.path.join(root, "*_r*.json")))


def main(root=None, argv=None):
    import json

    if argv:
        root = argv[0]
    sys.path.insert(0, root or _REPO)
    from garfield_tpu.telemetry import validate_jsonl

    paths = find_artifacts(root)
    if not paths:
        print("validate_artifacts: no *_r*.jsonl artifacts found",
              file=sys.stderr)
        return 1
    total = 0
    for path in paths:
        count = validate_jsonl(path)  # raises ValueError on drift
        total += count
        print(f"ok {os.path.relpath(path, root or _REPO)} "
              f"({count} records)")
    twins = 0
    for path in find_json_twins(root):
        with open(path) as fp:
            json.load(fp)  # raises on a torn/truncated capture
        twins += 1
    print(f"validate_artifacts: {len(paths)} artifacts, "
          f"{total} records, all schema-valid "
          f"(+{twins} parseable .json twins)")
    return 0


if __name__ == "__main__":
    sys.exit(main(argv=sys.argv[1:]))

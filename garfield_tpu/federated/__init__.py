"""Federated round engine (DESIGN.md §19): sharded PS plane + partial
participation at 10^6 clients.

The layer ABOVE the hierarchy: ``sharding`` partitions the flat
parameter vector across a PS shard group (the axis orthogonal to MSMW
replication), ``sampler`` prices a Byzantine budget per sampled cohort,
``engine`` runs the round loop (ingest -> per-shard hier-GAR ->
shard broadcast), and ``fleet`` drives simulated client processes
against a target round rate. ``apps/benchmarks/fed_bench.py`` is the
committed-record entry point (FEDBENCH_r*).
"""

from .engine import FedRoundEngine, ShardServer
from .fleet import ClientFleet, client_command
from .sampler import CohortSampler
from .sharding import (
    MAX_SHARDS,
    ShardSpec,
    plan_shards,
    reassemble,
    restore_sharded,
    save_sharded,
    shard_plane,
)

__all__ = [
    "MAX_SHARDS",
    "ShardSpec",
    "plan_shards",
    "shard_plane",
    "reassemble",
    "save_sharded",
    "restore_sharded",
    "CohortSampler",
    "ShardServer",
    "FedRoundEngine",
    "ClientFleet",
    "client_command",
]

"""Logging, registries and misc helpers.

Re-designed counterpart of pytorch_impl/libs/tools/__init__.py (colored
context-scoped logging :34-122, fatal :201-249) and tools/misc.py
(ClassRegister :118-172, pairwise :518-530, timing helpers :533-568).
"""

import itertools
import sys
import threading
import time

# ---------------------------------------------------------------------------
# Colored, context-scoped logging (reference tools/__init__.py:34-122)

_COLORS = {
    "info": "\033[0m",
    "warning": "\033[33m",
    "error": "\033[31m",
    "trace": "\033[90m",
}
_RESET = "\033[0m"
_print_lock = threading.Lock()
_use_color = sys.stderr.isatty()


class Context:
    """Scoped logging context: messages emitted inside a ``with Context(name)``
    block are prefixed with the nesting path, mirroring the reference's
    context-scoped logger (tools/__init__.py:34-122)."""

    _local = threading.local()

    def __init__(self, name):
        self.name = str(name)

    @classmethod
    def _stack(cls):
        if not hasattr(cls._local, "stack"):
            cls._local.stack = []
        return cls._local.stack

    def __enter__(self):
        self._stack().append(self.name)
        return self

    def __exit__(self, *exc):
        self._stack().pop()
        return False

    @classmethod
    def prefix(cls):
        stack = cls._stack()
        return ("[" + "/".join(stack) + "] ") if stack else ""


def _emit(level, *args):
    text = Context.prefix() + " ".join(str(a) for a in args)
    if _use_color:
        text = _COLORS.get(level, "") + text + _RESET
    with _print_lock:
        print(text, file=sys.stderr, flush=True)


def info(*args):
    _emit("info", *args)


def warning(*args):
    _emit("warning", "[W]", *args)


def trace(*args):
    _emit("trace", *args)


def fatal(*args, code=1):
    """Print an error and exit (reference tools/__init__.py:201-249)."""
    _emit("error", "[FATAL]", *args)
    sys.exit(code)


# ---------------------------------------------------------------------------
# Class register (reference tools/misc.py:118-172)

class ClassRegister:
    """Named registry of classes/callables with listing and error reporting."""

    def __init__(self, singular, plural=None):
        self._singular = singular
        self._plural = plural or (singular + "s")
        self._register = {}

    def register(self, name, cls):
        if name in self._register:
            raise KeyError(f"{self._singular} {name!r} already registered")
        self._register[name] = cls
        return cls

    def itemize(self):
        return sorted(self._register.keys())

    def __contains__(self, name):
        return name in self._register

    def __getitem__(self, name):
        try:
            return self._register[name]
        except KeyError:
            raise KeyError(
                f"unknown {self._singular} {name!r}; available "
                f"{self._plural}: {', '.join(self.itemize())}"
            ) from None

    def get(self, name, default=None):
        return self._register.get(name, default)

    def items(self):
        return self._register.items()


# ---------------------------------------------------------------------------
# Iteration helpers (reference tools/misc.py:518-530)

def pairwise(iterable):
    """All unordered pairs (x, y), x before y, of an iterable."""
    return itertools.combinations(iterable, 2)


# ---------------------------------------------------------------------------
# Timing helpers (reference tools/misc.py:533-568)

class Timer:
    """Wall-clock timer usable as a context manager; .elapsed in seconds."""

    def __init__(self):
        self.elapsed = 0.0
        self._start = None

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._start
        return False

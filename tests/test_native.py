"""Native (C++) kernel parity tests: elementwise agreement with the jit'd XLA
rules (the golden suite of SURVEY §4), NaN resilience, jit usability via
pure_callback, and the MRMW multibuffer register."""

import threading

import numpy as np
import pytest

from garfield_tpu import aggregators

pytestmark = pytest.mark.skipif(
    "native-krum" not in aggregators.gars,
    reason="native toolchain unavailable",
)


def _native():
    from garfield_tpu import native

    if not native.available():
        pytest.skip("native build failed")
    return native


def stacks():
    rng = np.random.default_rng(7)
    for n, d in [(7, 5), (11, 64), (15, 1), (23, 33)]:
        yield rng.standard_normal((n, d)).astype(np.float32)


@pytest.mark.parametrize("rule,f_of_n", [
    ("krum", lambda n: (n - 3) // 2),
    ("median", lambda n: 1),
    ("bulyan", lambda n: (n - 3) // 4),
    ("brute", lambda n: min((n - 1) // 2, 3)),
])
def test_native_matches_xla(rule, f_of_n):
    native = _native()
    for g in stacks():
        n = g.shape[0]
        f = f_of_n(n)
        if f < 1:
            continue
        kwargs = {} if rule == "median" else {"f": f}
        want = np.asarray(aggregators.gars[rule].unchecked(g, **kwargs))
        got = getattr(native, rule)(g, **kwargs)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_native_median_nan_resilient():
    native = _native()
    g = np.array(
        [[1.0, np.nan], [2.0, 5.0], [3.0, 4.0], [4.0, np.nan], [5.0, 6.0]],
        dtype=np.float32,
    )
    want = np.asarray(aggregators.gars["median"].unchecked(g))
    got = native.median(g)
    np.testing.assert_allclose(got, want)
    assert np.isfinite(got).all()


def test_native_krum_excludes_nan_row():
    native = _native()
    rng = np.random.default_rng(3)
    g = rng.standard_normal((9, 16)).astype(np.float32)
    g[8] = np.nan  # Byzantine row: infinite distances, never selected
    f = 2
    want = np.asarray(aggregators.gars["krum"].unchecked(g, f=f))
    got = native.krum(g, f=f)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
    assert np.isfinite(got).all()


def test_native_float64():
    native = _native()
    g = np.random.default_rng(5).standard_normal((9, 12))
    got = native.krum(g, f=2)
    assert got.dtype == np.float64


def test_native_gar_inside_jit():
    import jax
    import jax.numpy as jnp

    _native()
    g = np.random.default_rng(11).standard_normal((9, 8)).astype(np.float32)

    @jax.jit
    def agg(stack):
        return aggregators.gars["native-krum"].unchecked(stack, f=2)

    got = np.asarray(agg(jnp.asarray(g)))
    want = np.asarray(aggregators.gars["krum"].unchecked(g, f=2))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_multibuffer_blocking_handoff():
    native = _native()
    mb = native.MultiBuffer(2)
    assert mb.version(0) == 0
    got = {}

    def reader():
        got["v"], got["data"] = mb.read(0, min_version=2)

    t = threading.Thread(target=reader)
    t.start()
    mb.write(0, b"first")
    mb.write(0, b"second")  # last-writer-wins register
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["v"] == 2 and got["data"] == b"second"
    with pytest.raises(TimeoutError):
        mb.read(1, min_version=1, timeout_ms=50)
    mb.close()

// Work-stealing-free fixed threadpool with a blocking parallel_for.
//
// Native-parity counterpart of the reference's pool
// (pytorch_impl/libs/native/include/threadpool.hpp, 222 LoC mutex/condvar
// pool with parallel_for at :202) — re-implemented from scratch: a shared
// pool of hardware_concurrency workers, jobs are [begin, end) index ranges
// split into contiguous chunks, submitter blocks until completion.
#pragma once

#include <condition_variable>
#include <cstddef>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace garfield {

class ThreadPool {
 public:
  static ThreadPool& shared() {
    static ThreadPool pool;
    return pool;
  }

  explicit ThreadPool(std::size_t nthreads = 0) {
    if (nthreads == 0) {
      nthreads = std::thread::hardware_concurrency();
      if (nthreads == 0) nthreads = 1;
    }
    workers_.reserve(nthreads);
    for (std::size_t t = 0; t < nthreads; ++t) {
      workers_.emplace_back([this] { worker_loop(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  std::size_t size() const { return workers_.size(); }

  // Run fn(i) for i in [begin, end), splitting the range into one contiguous
  // chunk per worker; blocks until every index has been processed.
  void parallel_for(std::size_t begin, std::size_t end,
                    const std::function<void(std::size_t, std::size_t)>& body) {
    const std::size_t total = end > begin ? end - begin : 0;
    if (total == 0) return;
    const std::size_t nchunks =
        total < workers_.size() ? total : workers_.size();
    if (nchunks <= 1) {
      body(begin, end);
      return;
    }
    const std::size_t chunk = (total + nchunks - 1) / nchunks;
    // Completion state guarded by done_mu: decrement AND notify happen under
    // the lock, so the waiter cannot observe pending==0 and destroy these
    // stack locals while a worker still holds or is about to take the lock.
    std::size_t pending = nchunks;
    std::mutex done_mu;
    std::condition_variable done_cv;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (std::size_t c = 0; c < nchunks; ++c) {
        const std::size_t lo = begin + c * chunk;
        const std::size_t hi = lo + chunk < end ? lo + chunk : end;
        jobs_.push_back([&, lo, hi] {
          body(lo, hi);
          std::lock_guard<std::mutex> dlk(done_mu);
          if (--pending == 0) done_cv.notify_one();
        });
      }
    }
    cv_.notify_all();
    std::unique_lock<std::mutex> dlk(done_mu);
    done_cv.wait(dlk, [&] { return pending == 0; });
  }

 private:
  void worker_loop() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
        if (stop_ && jobs_.empty()) return;
        job = std::move(jobs_.back());
        jobs_.pop_back();
      }
      job();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<std::function<void()>> jobs_;
  std::vector<std::thread> workers_;
  bool stop_ = false;
};

// Convenience: parallel loop over single indices.
inline void parallel_for_each(
    std::size_t begin, std::size_t end,
    const std::function<void(std::size_t)>& fn) {
  ThreadPool::shared().parallel_for(
      begin, end, [&](std::size_t lo, std::size_t hi) {
        for (std::size_t i = lo; i < hi; ++i) fn(i);
      });
}

}  // namespace garfield

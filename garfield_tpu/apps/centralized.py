"""Centralized baseline: one worker, one server, no distribution.

Counterpart of ``pytorch_impl/applications/Centralized/trainer.py`` (P16):
local Worker + Server objects wired without RPC. Here it is the AggregaThor
topology degenerated to num_workers=1, f=0, gar=average on a 1-device mesh —
the SPMD program contains no collectives at all, so XLA compiles a purely
local train step.

  python -m garfield_tpu.apps.centralized --model convnet --dataset mnist
"""

import sys

from ..parallel import aggregathor
from . import common


def main(argv=None):
    parser = common.base_parser("Centralized training baseline (garfield-tpu)")
    args = parser.parse_args(argv)
    args.num_workers = 1
    args.fw = 0
    args.attack = None
    if not args.mesh:
        args.mesh = "workers=1"  # single-device program, no collectives
    return common.train(
        args,
        topology=aggregathor,
        make_trainer_kwargs=dict(num_workers=1, f=0),
        num_slots=1,
        tag="centralized",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

"""Host-plane publish/collect round benchmark + cluster-mode steps/s.

The committed record for the ``apps/cluster.py`` path (VERDICT r5 item 4:
no step-time number existed for the host plane at all). Two modes:

**Micro** (default): for each (n, d, wire) cell, n localhost OS processes
— rank 0 in this process, ranks 1..n-1 spawned — run ``--rounds``
rank-0-paced publish/collect round trips per trial over a REAL
``PeerExchange`` (TCP frames + the native MRMW register), every frame
through the typed wire codec (``utils/wire.py``) with eager decode in the
collect waiter threads (the shipped cluster path; see ``_rank0_rounds``
for why the pacing is what makes the rounds loss-free on the
last-writer-wins register). Rank 0 records the median round latency per
trial and commits the MIN over ``--trials`` (gar_bench's min-over-k:
co-tenant noise only adds time). ``wire_bytes_per_step`` is the per-node
DCN fan-out: (n-1) frames of ``wire.frame_nbytes(d, w)`` — the number the
bf16 codec halves.

**--e2e**: additionally runs the SSMW cluster deployment end-to-end
(1 PS + ``--e2e_workers`` worker subprocesses, mnist/convnet,
JAX_PLATFORMS=cpu) once per wire dtype with ``--telemetry``, and derives
steps/s from the PS's per-step ``step_time_s`` records (median over the
post-warmup steps — the BASELINE.md cluster-mode row) plus wire
bytes/step from the summary's wire totals.

  python -m garfield_tpu.apps.benchmarks.exchange_bench \\
      --ns 2 4 --ds 1000 100000 1000000 --wire f32 bf16 \\
      --json EXCHBENCH_r01.json --e2e
"""

import argparse
import json
import os
import socket
import statistics
import subprocess
import sys
import time

import numpy as np

from ...utils import wire
from ...utils.exchange import PeerExchange

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _decode_tf(idx, payload):
    return wire.decode(payload)


def _barrier(ex, n):
    """Startup barrier: everyone publishes a hello at step 0 and waits
    for every peer's — the micro rounds must time the exchange, not
    subprocess startup skew."""
    ex.publish(0, b"up")
    for r in range(n):
        if r != ex.my_index:
            ex.read_latest(r, 0, timeout_ms=120_000)


def _rank0_rounds(ex, n, d, wire_dtype, rounds, trials):
    """Rank 0 PACES the mesh, SSMW-style: publish the round's frame to
    every peer, collect every peer's typed response (eager decode in the
    waiter threads — the shipped cluster path). The pacing is the
    loss-freedom proof on the last-writer-wins register: a peer publishes
    round s only after reading rank 0's s, and rank 0 publishes s+1 only
    after collecting EVERY peer's s — so no round frame can be
    overwritten before its reader latched it. (A free-running symmetric
    protocol drops rounds here: two back-to-back writes from a fast peer
    land before the blocked reader is scheduled, and the register keeps
    only the newer — the exact race apps/cluster's role pacing closes.)
    Round latency = encode + fan-out + per-peer read/decode/re-encode/
    respond + collect + eager decode: two wire hops, the PS step's wire
    component. Returns the min-over-trials of the per-trial median."""
    rng = np.random.default_rng(1234)
    vec = rng.standard_normal(d).astype(np.float32)
    _barrier(ex, n)
    step = 1
    per_trial = []
    for _ in range(max(1, trials)):
        lats = []
        for _ in range(rounds):
            wait = ex.collect_begin(step, n, timeout_ms=120_000,
                                    transform=_decode_tf)
            t0 = time.perf_counter()
            ex.publish(step, wire.encode(vec, wire_dtype))
            got = wait()
            lats.append(time.perf_counter() - t0)
            assert len(got) == n and not any(
                isinstance(v, Exception) for v in got.values()
            )
            step += 1
        per_trial.append(statistics.median(lats))
    return min(per_trial) if per_trial else None


def _child_main(args):
    hosts = args.hosts.split(",")
    n = len(hosts)
    ex = PeerExchange(args.child, hosts, connect_retry_ms=120_000)
    rng = np.random.default_rng(1234 + args.child)
    vec = rng.standard_normal(args.d).astype(np.float32)
    try:
        _barrier(ex, n)
        for step in range(1, 1 + args.rounds * max(1, args.trials)):
            got = ex.collect(step, 1, peers=[0], timeout_ms=120_000,
                             transform=_decode_tf)
            assert not isinstance(got[0], Exception)
            ex.publish(step, wire.encode(vec, args.child_wire), to=[0])
    finally:
        ex.close()


def _spawn_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        _REPO + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else _REPO
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    return env


def bench_cell(n, d, wire_dtype, rounds, trials):
    """One micro cell: spawn ranks 1..n-1, run rank 0 here."""
    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    env = _spawn_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m",
             "garfield_tpu.apps.benchmarks.exchange_bench",
             "--child", str(k), "--hosts", ",".join(hosts),
             "--d", str(d), "--rounds", str(rounds),
             "--trials", str(trials), "--child_wire", wire_dtype],
            env=env,
        )
        for k in range(1, n)
    ]
    ex = PeerExchange(0, hosts, connect_retry_ms=120_000)
    try:
        round_s = _rank0_rounds(ex, n, d, wire_dtype, rounds, trials)
    finally:
        ex.close()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
    return {
        "mode": "micro", "n": n, "d": d, "wire": wire_dtype,
        "round_s": round_s,
        "wire_bytes_per_step": (n - 1) * wire.frame_nbytes(d, wire_dtype),
        "rounds": rounds, "trials": trials,
    }


def bench_e2e(wire_dtype, n_w, iters, tmpdir):
    """End-to-end SSMW cluster run (1 PS + n_w worker subprocesses) at
    ``wire_dtype``; steps/s from the PS's telemetry step records (median
    ``step_time_s`` over the post-warmup steps — compile-free, unlike
    wall_s / steps), wire bytes/step from the summary totals."""
    from ...utils import multihost

    pp = _ports(1 + n_w)
    cfg_path = os.path.join(tmpdir, f"cluster_{wire_dtype}.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _spawn_env()
    env["GARFIELD_WIRE_DTYPE"] = wire_dtype
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    tele_dir = os.path.join(tmpdir, f"tele_{wire_dtype}")

    def launch(role):
        return subprocess.Popen(
            [sys.executable, "-m", "garfield_tpu.apps.aggregathor",
             "--cluster", cfg_path, "--task", role,
             "--dataset", "mnist", "--model", "convnet", "--batch", "16",
             "--fw", "1", "--gar", "median", "--num_iter", str(iters),
             "--acc_freq", "0", "--train_size", "512",
             "--cluster_timeout_ms", "120000", "--telemetry", tele_dir],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    ps = launch("ps:0")
    workers = [launch(f"worker:{w}") for w in range(n_w)]
    try:
        out, _ = ps.communicate(timeout=600 + 10 * iters)
        if ps.returncode != 0:
            raise RuntimeError(f"e2e PS failed:\n{out[-2000:]}")
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        for w in workers:
            w.communicate(timeout=120)
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()
    step_times, wire_totals = [], None
    with open(os.path.join(tele_dir, "cluster-ps.telemetry.jsonl")) as fp:
        for line in fp:
            rec = json.loads(line)
            if rec["kind"] == "step" and rec.get("step_time_s") is not None:
                step_times.append((rec["step"], rec["step_time_s"]))
            elif rec["kind"] == "summary":
                wire_totals = rec.get("wire")
    # Warmup excluded: the first steps pay grad/update compiles and the
    # exchange's cold-start connect grace.
    warm = [t for s, t in step_times if s >= 5]
    med = statistics.median(warm) if warm else None
    steps = summary["steps"]
    return {
        "mode": "cluster_e2e", "wire": wire_dtype, "workers": n_w,
        "iters": iters, "steps": steps,
        "wall_s": round(summary["wall_s"], 3),
        "step_s_median": None if med is None else round(med, 6),
        "steps_per_s": None if not med else round(1.0 / med, 3),
        "wire_bytes_per_step": (
            None if not (wire_totals and steps) else
            int((wire_totals["bytes_out"] + wire_totals["bytes_in"])
                / steps)
        ),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="host-plane exchange/wire-codec benchmark"
    )
    p.add_argument("--ns", nargs="*", type=int, default=[2, 4])
    p.add_argument("--ds", nargs="*", type=int,
                   default=[1_000, 100_000, 1_000_000])
    p.add_argument("--wire", nargs="*", default=list(wire.WIRE_DTYPES),
                   choices=wire.WIRE_DTYPES)
    p.add_argument("--rounds", type=int, default=20,
                   help="publish/collect rounds per trial")
    p.add_argument("--trials", type=int, default=3,
                   help="independent trials; the committed value is the "
                        "min of the per-trial medians (min-over-k)")
    p.add_argument("--e2e", action="store_true",
                   help="also run the SSMW cluster deployment end-to-end "
                        "per wire dtype (the BASELINE.md row)")
    p.add_argument("--e2e_workers", type=int, default=4)
    p.add_argument("--e2e_iters", type=int, default=40)
    p.add_argument("--json", type=str, default=None,
                   help="dump results (+ the schema-versioned telemetry "
                        "JSONL twin at the same path with a .jsonl "
                        "suffix)")
    # child-process plumbing (internal)
    p.add_argument("--child", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--hosts", type=str, default=None, help=argparse.SUPPRESS)
    p.add_argument("--d", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--child_wire", type=str, default="f32",
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.child is not None:
        return _child_main(args)

    results = []
    for n in args.ns:
        for d in args.ds:
            for w in args.wire:
                row = bench_cell(n, d, w, args.rounds, args.trials)
                results.append(row)
                rs = row["round_s"]
                print(
                    f"n={n} d={d:<9} wire={w:<4} "
                    f"{'below noise floor' if rs is None else f'{rs * 1e3:9.3f} ms'}"
                    f"  {row['wire_bytes_per_step']:>12} B/step",
                    flush=True,
                )
    if args.e2e:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            for w in args.wire:
                row = bench_e2e(w, args.e2e_workers, args.e2e_iters, td)
                results.append(row)
                print(
                    f"e2e wire={w:<4} {row['steps_per_s']} steps/s "
                    f"({row['wire_bytes_per_step']} wire B/step)",
                    flush=True,
                )
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                if row["mode"] == "micro":
                    exp.write(exporters.make_record(
                        "exchange_bench",
                        n=row["n"], d=row["d"], wire=row["wire"],
                        round_s=row["round_s"],
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                        rounds=row["rounds"], trials=row["trials"],
                    ))
                else:
                    exp.write(exporters.make_record(
                        "bench",
                        metric=f"cluster_ssmw_steps_per_s_{row['wire']}",
                        value=row["steps_per_s"],
                        unit="steps/s",
                        wire_bytes_per_step=row["wire_bytes_per_step"],
                    ))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""Test configuration: force a virtual 8-device CPU platform.

This is the fake-backend the reference lacked (SURVEY §4): every distributed
construct is testable single-process by running the SPMD program over 8
host-local CPU devices.

The interpreter's sitecustomize preloads jax and registers the TPU PJRT
plugin before this file runs, so env vars alone are too late;
``jax.config.update`` still wins as long as no backend has been initialized —
it overrides the platform choice, sets the virtual CPU device count, and
keeps the TPU plugin from ever being initialized (its init can block on an
unavailable device tunnel). The env vars are still set for any subprocess a
test might spawn.
"""

import os

# GARFIELD_TPU_TESTS=1 opts OUT of the CPU forcing so the real-TPU test
# files (tests/test_ops_tpu.py — on-device Mosaic-lowering equality) run
# against the chip; everything else skips itself off-CPU or on-TPU as
# appropriate.
_USE_TPU = os.environ.get("GARFIELD_TPU_TESTS", "").lower() not in (
    "", "0", "false",
)

if not _USE_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # Older jax spells the virtual device count only through XLA_FLAGS
        # (set above) — same 8-device CPU platform either way.
        pass

# Persistent compilation cache: CPU test compiles of the large SPMD programs
# dominate suite time; caching them across runs keeps the suite fast. The
# directory is keyed by the jax/jaxlib versions (same scheme as
# utils.profiling.enable_compile_cache): cached executables are not
# serialization-stable across jaxlib builds, and a stale entry from a
# previous container deserializes into a native SIGSEGV, not a catchable
# cache miss.
import jaxlib

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.expanduser(
        f"~/.cache/garfield_tpu/jax_cache-"
        f"{jax.__version__}-{jaxlib.__version__}"
    ),
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# End-to-end trainer files last. Alphabetical collection puts
# test_apps.py (ten full CLI training runs, ~1 min each on a 1-core
# container) FIRST, so a tier-1 wall-clock budget hit starves the entire
# unit matrix behind it. Run units first and the end-to-end runs last: a
# timeout then costs the slowest, most redundant coverage (the app flows
# are also exercised piecewise by the unit files), not the matrix.
# test_hierarchy_stream.py is end-to-end too (slow-marked multi-wave
# TCP-exchange ingest into the hierarchical reducer), as are the
# multi-PROCESS deployment suites (subprocess fleets over PeerExchange):
# test_multihost_integration.py, test_cluster.py, test_async_cluster.py.
# All collect before the app runs when slow tests are enabled.
_RUN_LAST = {
    "test_multihost_integration.py": 1,
    "test_hierarchy_stream.py": 2,
    "test_cluster.py": 3,
    "test_async_cluster.py": 4,
    "test_defense_cluster.py": 5,
    "test_dataplane_cluster.py": 6,
    "test_fed_cluster.py": 7,
    "test_apps.py": 8,
}

# Tier-1 wall-clock budget of the verify command (ROADMAP.md): the
# watchdog below warns when a run gets close, so a creeping suite is
# visible BEFORE the external timeout starts starving the e2e tail.
_TIER1_BUDGET_S = 870


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda it: _RUN_LAST.get(it.fspath.basename, 0))
    # Tier-1 budget discipline: any TIER-1 test (not slow-marked) that
    # drives a full CLI training run (the app_*.main pattern) must live
    # in a file REGISTERED in _RUN_LAST, so a wall-clock budget hit
    # starves the slowest, most redundant end-to-end coverage — never
    # the unit matrix collected behind it. A new e2e-style test added
    # outside the registered files fails here at collection instead of
    # silently eating the tier-1 budget first. (Slow-marked app runs are
    # exempt: they never enter the tier-1 shard.)
    import inspect
    import re

    pattern = re.compile(r"\bapp_\w+\.main\(")
    src_cache = {}
    file_src_cache = {}
    popen = re.compile(r"\bsubprocess\.Popen\b")
    garfield = re.compile(r"garfield_tpu\.(apps|utils\.multihost)|"
                          r"multihost_child")
    for it in items:
        fn = getattr(it, "function", None)
        if fn is None:
            continue
        # Multi-process e2e discipline: a FILE that spawns garfield
        # subprocess fleets (subprocess.Popen + app/multihost plumbing)
        # must be registered in _RUN_LAST — those files hold the most
        # expensive, most redundant coverage and must collect last even
        # in full-suite runs; a new one fails here at collection.
        path = str(it.fspath)
        if path not in file_src_cache:
            try:
                with open(path) as fp:
                    src = fp.read()
            except OSError:
                src = ""
            file_src_cache[path] = bool(
                popen.search(src) and garfield.search(src)
            )
        assert not file_src_cache[path] or (
            it.fspath.basename in _RUN_LAST
        ), (
            f"{it.fspath.basename} spawns garfield subprocess fleets "
            "(multi-process e2e) but is not registered in "
            "conftest._RUN_LAST — register it so the unit matrix keeps "
            "collection priority"
        )
        if (it.get_closest_marker("slow") is not None
                or it.fspath.basename in _RUN_LAST):
            continue
        if fn not in src_cache:
            try:
                src_cache[fn] = bool(pattern.search(inspect.getsource(fn)))
            except (OSError, TypeError):
                src_cache[fn] = False
        assert not src_cache[fn], (
            f"{it.nodeid} drives a full app CLI run (app_*.main) from a "
            "tier-1 test outside conftest._RUN_LAST — move it to a "
            "registered end-to-end file (or slow-mark it) so the unit "
            "matrix keeps collection priority (tier-1 budget discipline)"
        )


def pytest_sessionstart(session):
    import time

    session._garfield_t0 = time.time()


def pytest_sessionfinish(session, exitstatus):
    # Tier-1 budget watchdog: the fast shard (-m 'not slow') must stay
    # under the verify command's 870 s timeout on the 1-core box. Warn
    # at 90% so growth is caught in review, not as a truncated CI run.
    import sys
    import time

    markexpr = getattr(session.config.option, "markexpr", "") or ""
    if "not slow" not in markexpr:
        return
    wall = time.time() - getattr(session, "_garfield_t0", time.time())
    if wall > 0.9 * _TIER1_BUDGET_S:
        print(
            f"\n[tier-1 budget watchdog] fast shard took {wall:.0f}s — "
            f"{'OVER' if wall > _TIER1_BUDGET_S else 'within 10% of'} "
            f"the {_TIER1_BUDGET_S}s budget; trim or slow-mark the "
            "newest fast tests (conftest._TIER1_BUDGET_S)",
            file=sys.stderr,
        )

"""Cross-process cluster trainer: real wait-n-f straggler/crash tolerance.

VERDICT r2 #3: the host-level async exchange must be CONSUMED by a training
path, not just unit-tested. These launch the reference's deployment shape
(run_exp.sh fan-out: one OS process per node) — 1 PS + 4 workers over
PeerExchange — and exercise the two fault classes end-to-end: a mid-run
SIGKILL (survivors keep training: the PS's per-step quorum is the
q = n_w - f = 3 FASTEST gradients, server.py:134-155, so the dead worker
is simply absent from every later quorum) and a live Byzantine attacker
process. (q of at least 3 matters for learning quality, not just
tolerance: the coordinate-wise LOWER median of a q = 2 quorum is the
elementwise min — a biased aggregate.)
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

pytest.importorskip("garfield_tpu.native")

# Multi-process deployments compile per process: minutes per test by design.
# The tier-1 fast shard (-m "not slow") skips them; CI runs the full suite.
pytestmark = pytest.mark.slow
from garfield_tpu import native

if native.load() is None:
    pytest.skip("native runtime unavailable", allow_module_level=True)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _cluster_setup(tmp_path, n_w):
    """(cfg_path, env) for an n_w-worker localhost deployment.

    The env pins an easy surrogate margin: these tests are about fault
    tolerance, not task difficulty — the default margin is deliberately
    hard (hundreds of steps to climb; data/__init__.py).
    """
    from garfield_tpu.utils import multihost

    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    return cfg_path, _subprocess_env()


def _subprocess_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    # Deliberately NO persistent compile cache for the subprocess fleets:
    # on this host the XLA:CPU AOT loader rejects its own entries
    # (machine-feature validation), and the per-jit failed loads + error
    # spam starved worker startup past the PS quorum budget (r5).
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def _launch(role, cfg_path, env, extra=(), module="aggregathor"):
    return subprocess.Popen(
        [
            sys.executable, "-m", f"garfield_tpu.apps.{module}",
            "--cluster", cfg_path, "--task", role,
            "--dataset", "mnist", "--model", "convnet", "--batch", "16",
            "--fw", "1", "--gar", "median", "--num_iter", "60",
            "--acc_freq", "10", "--train_size", "512",
            "--cluster_timeout_ms", "120000", *extra,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def _assert_ps_converges(ps, workers, tag, steps=60, timeout=400):
    """Shared tail of the convergence tests: PS exits 0 with all steps done,
    accuracy improves over step 0, every worker exits 0; processes are
    killed on any failure path."""
    try:
        out, _ = ps.communicate(timeout=timeout)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == steps
        first_acc = float(
            [l for l in out.splitlines() if l.startswith("Step: 0 ")][0]
            .split()[3]
        )
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
            f"{tag}: {summary}"
        )
        for w in workers:
            wout, _ = w.communicate(timeout=120)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()


def test_robust_stats_trims_byzantine_row():
    """The BN-stat plane carries the f budget (ADVICE r4 medium): a
    Byzantine process's arbitrary stat row must not leak through the
    aggregation; f=0 stays the plain on-mesh mean."""
    import numpy as np

    from garfield_tpu.apps.cluster import _robust_stats

    rng = np.random.default_rng(0)
    honest = rng.normal(size=(5, 7)).astype(np.float32)
    byz = np.full((1, 7), 1e9, np.float32)
    out = _robust_stats(np.concatenate([honest, byz]), f=1)
    assert np.abs(out).max() < 10.0
    np.testing.assert_allclose(
        _robust_stats(honest, 0), honest.mean(axis=0), rtol=1e-6
    )
    one = np.ones((1, 3), np.float32)  # trim clamps; never empties
    np.testing.assert_allclose(_robust_stats(one, 5), one[0])


def _msmw_setup(tmp_path, n_ps, n_w):
    from garfield_tpu.utils import multihost

    pp = _ports(n_ps + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{p}" for p in pp[:n_ps]],
        workers=[f"127.0.0.1:{p}" for p in pp[n_ps:]],
        task_type="ps", task_index=0,
    )
    env = _subprocess_env()
    return cfg_path, env


def test_msmw_ps_crash_survivors_degrade_and_converge(tmp_path):
    """Crash degradation (VERDICT r4 #7): SIGKILL one of 3 PS replicas
    mid-run; the survivors must declare it dead, shrink the model plane
    (loudly), and complete all steps with improving accuracy — the
    reference's pull loops would bounded-retry and exit instead
    (server.py:138-141)."""
    n_ps, n_w = 3, 3
    cfg_path, env = _msmw_setup(tmp_path, n_ps, n_w)
    n_iter = 60
    extra = (
        "--fps", "1", "--model_gar", "median", "--num_iter", str(n_iter),
        "--cluster_timeout_ms", "25000",
    )
    pses = [
        _launch(f"ps:{p}", cfg_path, env, module="byzsgd", extra=extra)
        for p in range(n_ps)
    ]
    workers = [
        _launch(f"worker:{w}", cfg_path, env, module="byzsgd", extra=extra)
        for w in range(n_w)
    ]
    try:
        time.sleep(25)  # let the deployment form, then kill a replica
        pses[2].send_signal(signal.SIGKILL)
        survivor_outs = []
        for p_idx in (0, 1):
            out, _ = pses[p_idx].communicate(timeout=400 + 8 * n_iter)
            assert pses[p_idx].returncode == 0, (
                f"survivor PS {p_idx} failed:\n{out[-2000:]}"
            )
            survivor_outs.append(out)
            summary = json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            )
            assert summary["steps"] == n_iter
            assert summary["final_accuracy"] > 0.3, summary
        assert any("degraded" in o for o in survivor_outs), (
            "no degradation warning was logged"
        )
        for w in workers:
            wout, _ = w.communicate(timeout=200)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
    finally:
        for p in [*pses, *workers]:
            if p.poll() is None:
                p.kill()


def test_msmw_checkpoint_resume(tmp_path):
    """Multi-PS checkpoint/resume (VERDICT r4 #4, lifting the r4
    rejection): each replica persists under checkpoint_dir/ps_{i}; a full
    restart with --resume restores step 30 on every replica and finishes
    the remaining steps (workers catch up through the model plane)."""
    n_ps, n_w = 2, 3
    cfg_path, env = _msmw_setup(tmp_path, n_ps, n_w)
    ckpt = str(tmp_path / "ckpt")
    base = (
        "--fps", "0", "--model_gar", "average",
        "--checkpoint_dir", ckpt, "--checkpoint_freq", "10",
    )

    def run(n_iter, resume):
        extra = base + ("--num_iter", str(n_iter)) + (
            ("--resume",) if resume else ()
        )
        pses = [
            _launch(f"ps:{p}", cfg_path, env, module="byzsgd", extra=extra)
            for p in range(n_ps)
        ]
        workers = [
            _launch(f"worker:{w}", cfg_path, env, module="byzsgd",
                    extra=extra)
            for w in range(n_w)
        ]
        outs = []
        try:
            for i, p in enumerate(pses):
                out, _ = p.communicate(timeout=600)
                assert p.returncode == 0, f"PS {i} failed:\n{out[-2000:]}"
                outs.append(out)
            for w in workers:
                wout, _ = w.communicate(timeout=200)
                assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
        finally:
            for p in [*pses, *workers]:
                if p.poll() is None:
                    p.kill()
        return outs

    run(30, resume=False)
    import os as _os

    for p in range(n_ps):
        assert _os.path.isdir(_os.path.join(ckpt, f"ps_{p}")), (
            "per-replica checkpoint directory missing"
        )
    outs = run(60, resume=True)
    for i, out in enumerate(outs):
        assert "resumed from step 30" in out, (
            f"PS {i} did not resume:\n{out[-1500:]}"
        )
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 60


def _learn_setup(tmp_path, n, name="learn.json"):
    from garfield_tpu.utils import multihost

    pp = _ports(n)
    cfg_path = str(tmp_path / name)
    multihost.generate_config(
        cfg_path, nodes=[f"127.0.0.1:{p}" for p in pp],
        task_type="node", task_index=0,
    )
    return cfg_path, _subprocess_env()


def test_learn_cluster_batchnorm_stats_travel(tmp_path):
    """LEARN gossip BN plane (VERDICT r4 #4): on a BatchNorm architecture
    the model-gossip frames carry [params || stats] and every node adopts
    the robust-aggregated statistics — the strict frame-length contract
    makes a clean multi-round run the proof that the extended layout
    round-trips on the decentralized topology (the on-mesh twin
    mean-syncs BN state every step, parallel/learn.py). 3 nodes x 2
    rounds: each node compiles the ResNet-class model from scratch on
    this 1-core host (~4-12 min total), so the round count stays minimal
    — the frame contract, not learning progress, is under test."""
    n = 3
    cfg_path, env = _learn_setup(tmp_path, n)
    extra = (
        "--dataset", "cifar10", "--model", "regnetx200", "--batch", "8",
        "--loss", "nll", "--fw", "1", "--gar", "median", "--num_iter", "2",
        "--train_size", "64", "--acc_freq", "0",
    )
    nodes = [
        _launch(f"node:{k}", cfg_path, env, module="learn", extra=extra)
        for k in range(n)
    ]
    try:
        for k, node in enumerate(nodes):
            out, _ = node.communicate(timeout=1500)
            assert node.returncode == 0, f"node {k} failed:\n{out[-2000:]}"
            summary = json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            )
            assert summary["steps"] == 2, summary
    finally:
        for p in nodes:
            if p.poll() is None:
                p.kill()


def test_learn_cluster_checkpoint_resume(tmp_path):
    """Per-node LEARN checkpoint/resume (VERDICT r4 #4): every peer
    persists its own model+optimizer under checkpoint_dir/node_{k}; a
    full-deployment restart with --resume restores the common step and
    finishes the remaining rounds. convnet keeps the compile cost of the
    two phases small — resume mechanics are model-independent (the BN
    frame layout is covered by the regnet test above)."""
    n = 4
    ckpt = str(tmp_path / "lck")
    base = (
        "--loss", "nll", "--num_iter", "6", "--acc_freq", "0",
        "--train_size", "256",
        "--checkpoint_dir", ckpt, "--checkpoint_freq", "3",
    )

    def run(n_iter, resume, cfg_path, env):
        extra = base + ("--num_iter", str(n_iter)) + (
            ("--resume",) if resume else ()
        )
        nodes = [
            _launch(f"node:{k}", cfg_path, env, module="learn", extra=extra)
            for k in range(n)
        ]
        outs = []
        try:
            for k, node in enumerate(nodes):
                out, _ = node.communicate(timeout=600)
                assert node.returncode == 0, (
                    f"node {k} failed:\n{out[-2000:]}"
                )
                outs.append(out)
        finally:
            for p in nodes:
                if p.poll() is None:
                    p.kill()
        return outs

    cfg_path, env = _learn_setup(tmp_path, n)
    run(6, resume=False, cfg_path=cfg_path, env=env)
    cfg_path, env = _learn_setup(tmp_path, n, name="learn2.json")
    outs = run(10, resume=True, cfg_path=cfg_path, env=env)
    resumed = sum("resumed from step 6" in o for o in outs)
    assert resumed == n, f"only {resumed}/{n} nodes resumed"
    for out in outs:
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 10, summary


@pytest.mark.parametrize("wdtype", ["f32", "bf16"])
def test_cluster_wire_dtype_convergence_under_lie(tmp_path, wdtype):
    """The wire-codec convergence smoke (ISSUE r8 acceptance): the 8-rank
    deployment (1 PS + 7 workers) converges under a REAL lie-attack
    process at BOTH wire widths. f32 keeps payload bytes identical to the
    pre-codec format (trajectory parity); bf16 halves every frame on the
    wire and the quantization must stay inside what median's f budget
    absorbs (utils/wire.py docstring — the on-mesh bf16 pipeline already
    proved the precision is sufficient, PERF.md r3)."""
    n_w = 7
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    env["GARFIELD_WIRE_DTYPE"] = wdtype
    n_iter = 120
    extra = (
        "--fw", "2", "--num_iter", str(n_iter),
    )
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=extra + (
                ("--attack", "lie", "--attack_params", '{"cohort": 2}')
                if w == n_w - 1 else ()
            ),
        )
        for w in range(n_w)
    ]
    _assert_ps_converges(
        ps, workers,
        f"median did not ride out the lie attacker on {wdtype} wire",
        steps=n_iter, timeout=500 + 5 * n_iter,
    )


def test_byzantine_worker_process_tolerated(tmp_path):
    """A REAL Byzantine process (not an on-mesh emulation): worker 3 runs
    with --attack reverse (publishes -100x its gradient, byzWorker.py
    semantics) for the whole run; the PS's median over the q = 3 fastest
    of 4 gradients must still converge. This is the GAR doing its actual
    job across OS processes. (No watchdog: every wait below is already
    timeout-bounded.)"""
    n_w = 4
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    # 120 iters (vs 60 elsewhere): the PS quorum is the 3 FASTEST of 4, so
    # under full-suite CPU contention the Byzantine worker lands in the
    # quorum more often than in an isolated run — convergence still holds
    # (median of 3 with 1 byz row is bounded by the honest pair) but needs
    # more steps of headroom to clear the accuracy bar deterministically.
    n_iter = 120
    ps = _launch("ps:0", cfg_path, env, extra=("--num_iter", str(n_iter)))
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=(("--num_iter", str(n_iter))
                   + (("--attack", "reverse") if w == n_w - 1 else ())),
        )
        for w in range(n_w)
    ]
    _assert_ps_converges(
        ps, workers, "median did not ride out the Byzantine worker",
        steps=n_iter, timeout=400 + 5 * n_iter,
    )


def test_cluster_momentum_cclip_defense(tmp_path):
    """The worker-momentum + cclip defense in the TRUE deployment shape:
    every process publishes its gradient EMA (plain-SGD server, the
    required pairing — BASELINE.md), the PS clips, and a real Byzantine
    process attacking with reverse x(-100) cannot stop convergence."""
    n_w = 4
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    # lr 0.2 is the TTA-proven stable pairing for wm 0.9 on a plain-SGD
    # server (BASELINE.md: lr 0.5 climbs then COLLAPSES late — the worker
    # EMA's lag destabilizes the hot step; this test first sampled before
    # the collapse and flaked). The effective rate is 5x below the median
    # twin's (which runs a momentum server), and the PS proceeds with the
    # q = 3 fastest workers while subprocess startup staggers by tens of
    # seconds on this 1-core box — so give the surviving quorum 400 steps.
    n_iter = 400
    defense = (
        "--gar", "cclip", "--worker_momentum", "0.9",
        "--opt_args", '{"lr":"0.2"}', "--num_iter", str(n_iter),
    )
    ps = _launch("ps:0", cfg_path, env, extra=defense)
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=defense + (
                ("--attack", "reverse") if w == n_w - 1 else ()
            ),
        )
        for w in range(n_w)
    ]
    _assert_ps_converges(
        ps, workers, "cclip+momentum did not ride out the Byzantine worker",
        steps=n_iter, timeout=400 + 5 * n_iter,
    )


def test_byzsgd_cluster_byzantine_ps_tolerated(tmp_path):
    """Multi-process ByzSGD (MSMW): every PS a REAL process, one of them
    Byzantine. 3 PS replicas (1-of-2 Byzantine is information-theoretically
    untolerable, so the minimal honest-majority deployment is 3 with
    fps=1) x 4 workers; PS 2 runs --ps_attack reverse and publishes
    -100x its model every step (byzServer.py:86-108 as a live process).
    Every node GAR-aggregates the 3 models with median before use
    (the gather step, ByzSGD/trainer.py:240-244), so the honest replicas
    must converge."""
    n_ps, n_w = 3, 4
    from garfield_tpu.utils import multihost

    pp = _ports(n_ps + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{p}" for p in pp[:n_ps]],
        workers=[f"127.0.0.1:{p}" for p in pp[n_ps:]],
        task_type="ps", task_index=0,
    )
    env = _subprocess_env()
    n_iter = 60
    base = (
        "--fps", "1", "--model_gar", "median", "--num_iter", str(n_iter),
    )
    pses = [
        _launch(
            f"ps:{p}", cfg_path, env, module="byzsgd",
            extra=base + (
                ("--ps_attack", "reverse") if p == n_ps - 1 else ()
            ),
        )
        for p in range(n_ps)
    ]
    workers = [
        _launch(f"worker:{w}", cfg_path, env, module="byzsgd", extra=base)
        for w in range(n_w)
    ]
    procs = pses + workers
    try:
        for p_idx, ps in enumerate(pses):
            out, _ = ps.communicate(timeout=400 + 5 * n_iter)
            assert ps.returncode == 0, f"PS {p_idx} failed:\n{out[-2000:]}"
            if p_idx == n_ps - 1:
                continue  # the Byzantine replica's own numbers are garbage
            summary = json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            )
            assert summary["steps"] == n_iter
            first_acc = float(
                [l for l in out.splitlines() if l.startswith("Step: 0 ")][0]
                .split()[3]
            )
            assert summary["final_accuracy"] > max(0.3, first_acc + 0.1), (
                f"honest PS {p_idx} did not converge: {summary}"
            )
        for w in workers:
            wout, _ = w.communicate(timeout=120)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_learn_cluster_node_crash_survivors_converge(tmp_path):
    """Multi-process LEARN: every node a real worker+server process
    gossiping gradients AND models over PeerExchange at per-node wait-n-f
    (LEARN/trainer.py:224-257). One of 5 nodes is SIGKILLed mid-run; the
    survivors' q = n - f = 3 quorums flow around the corpse on both
    planes. f=2 (not 1) so the budget covers the kill PLUS one
    contention straggler: at q = survivors the quorums have zero slack
    and a single 120 s starvation on this 1-core box cascades into a
    full stall (observed in full-suite runs)."""
    n = 5
    from garfield_tpu.utils import multihost

    pp = _ports(n)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        nodes=[f"127.0.0.1:{p}" for p in pp],
        task_type="node", task_index=0,
    )
    env = _subprocess_env()
    n_iter = 60
    # the learn app defaults to --loss bce (pima); this test runs mnist.
    # --fw 2 overrides _launch's default fw=1 (see docstring).
    extra = ("--num_iter", str(n_iter), "--loss", "nll", "--fw", "2")
    nodes = [
        _launch(f"node:{k}", cfg_path, env, module="learn", extra=extra)
        for k in range(n)
    ]
    victim = nodes[-1]
    watchdog = threading.Timer(900, lambda: [p.kill() for p in nodes])
    watchdog.start()
    try:
        # Wait until training is demonstrably under way on node 0, then
        # SIGKILL the last node — a hard crash mid-gossip.
        first_acc = None
        head = []
        for line in nodes[0].stdout:
            head.append(line)
            if line.startswith("Step: 0 "):
                first_acc = float(line.split()[3])
            if line.startswith("Step: 10 "):
                break
        assert first_acc is not None, (
            "node 0 never reported step-0 accuracy:\n" + "".join(head)[-2000:]
        )
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        rest = "".join(head) + nodes[0].stdout.read()
        nodes[0].wait(timeout=600)
        watchdog.cancel()
        outs = [rest]
        for k in (1, 2, 3):
            out, _ = nodes[k].communicate(timeout=600)
            outs.append(out)
        # System-level guarantee, not per-node: every survivor exits
        # cleanly (a box-contention straggler may gracefully drop out —
        # the bounded-retry semantics — but must not crash), and the
        # quorum flow survives the kill: at least 3 of the 4 survivors
        # complete all rounds and converge.
        finished = 0
        for k, out in enumerate(outs):
            assert nodes[k].returncode == 0, (
                f"node {k} failed:\n{out[-2000:]}"
            )
            json_lines = [
                l for l in out.splitlines() if l.startswith("{")
            ]
            assert json_lines, f"node {k} printed no summary:\n{out[-1500:]}"
            summary = json.loads(json_lines[-1])
            if summary["steps"] == n_iter:
                assert summary["final_accuracy"] > max(
                    0.3, first_acc + 0.1
                ), f"node {k} finished but did not converge: {summary}"
                finished += 1
        assert finished >= 3, (
            f"only {finished}/4 survivors completed all {n_iter} rounds"
        )
    finally:
        watchdog.cancel()
        for p in nodes:
            if p.poll() is None:
                p.kill()


def test_cluster_batchnorm_stats_travel(tmp_path):
    """SSMW BN-stat exchange (VERDICT r3 weak #5): on a BatchNorm model the
    gradient frames carry [grad || batch_stats] and the model frames
    [params || mean stats]; the strict frame-length contracts on both ends
    make a clean 4-iter run the proof that the extended layout round-trips
    (any mismatch raises/excludes). regnetx200 is the smallest BN model in
    the zoo (2.3M params, 21k stats)."""
    n_w = 2
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    extra = (
        "--dataset", "cifar10", "--model", "regnetx200", "--batch", "8",
        "--fw", "0", "--gar", "average", "--num_iter", "4",
        "--train_size", "64", "--acc_freq", "0",
    )
    ps = _launch("ps:0", cfg_path, env, extra=extra)
    workers = [
        _launch(f"worker:{w}", cfg_path, env, extra=extra)
        for w in range(n_w)
    ]
    try:
        # Budget for three concurrent cold ResNet-class compiles (grad +
        # scanned-eval programs) on this 1-core host.
        out, _ = ps.communicate(timeout=900)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 4
        for w in workers:
            wout, _ = w.communicate(timeout=200)
            assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
            wsummary = json.loads(
                [l for l in wout.splitlines() if l.startswith("{")][-1]
            )
            assert wsummary["steps"] == 4
    finally:
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()


def test_cluster_momentum_cclip_defense_vs_lie(tmp_path):
    """The headline defense against the attack that motivated it, with a
    REAL process running the attack: the Byzantine worker computes its
    2-member cohort's honest momenta locally from its own batches
    (byzWorker.py:114-125 local-cohort trick) and publishes mu + z*sigma
    each step; cclip over the q = 4 fastest of 5 EMAs must still converge.
    Config is the TTA-proven stable pairing (wm 0.9 + plain-SGD server +
    lr 0.2 — see BASELINE.md and the r3 flake anatomy)."""
    n_w = 5
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    n_iter = 400
    defense = (
        "--gar", "cclip", "--worker_momentum", "0.9",
        "--opt_args", '{"lr":"0.2"}', "--num_iter", str(n_iter),
    )
    ps = _launch("ps:0", cfg_path, env, extra=defense)
    workers = [
        _launch(
            f"worker:{w}", cfg_path, env,
            extra=defense + (
                ("--attack", "lie", "--attack_params", '{"cohort": 2}')
                if w == n_w - 1 else ()
            ),
        )
        for w in range(n_w)
    ]
    _assert_ps_converges(
        ps, workers, "cclip+momentum did not ride out the lie attacker",
        steps=n_iter, timeout=400 + 5 * n_iter,
    )


def test_ps_checkpoint_resume(tmp_path):
    """PS-side checkpoint/resume: run 30 steps with checkpointing, then
    relaunch with --resume for 60 — the PS restores step 30 and the
    workers (which always start expecting round 0) catch up to the resumed
    round via read_latest, finishing the remaining 30 steps. Workers run
    --worker_momentum, so the resume also exercises the per-worker EMA
    persistence (ADVICE r3: the EMA is training state; without it a resume
    re-warms from zero while an attacker keeps full strength)."""
    n_w = 4
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    ckpt_dir = str(tmp_path / "ckpt")
    # wm 0.9 + plain-SGD server + lr 0.2 is the stable pairing (BASELINE.md)
    wm = (
        "--worker_momentum", "0.9", "--opt_args", '{"lr":"0.2"}',
        "--checkpoint_dir", ckpt_dir, "--checkpoint_freq", "10",
    )

    def run(extra_ps, extra_w=()):
        ps = _launch("ps:0", cfg_path, env, extra=wm + extra_ps)
        workers = [
            _launch(f"worker:{w}", cfg_path, env, extra=wm + extra_w)
            for w in range(n_w)
        ]
        try:
            out, _ = ps.communicate(timeout=400)
            assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
            wouts = []
            for w in workers:
                wout, _ = w.communicate(timeout=120)
                assert w.returncode == 0, f"worker failed:\n{wout[-1500:]}"
                wouts.append(wout)
            return out, wouts
        finally:
            for p in [ps, *workers]:
                if p.poll() is None:
                    p.kill()

    run(("--num_iter", "30"))
    # Every worker persisted its EMA at the checkpoint cadence.
    import numpy as np

    for w in range(n_w):
        with np.load(tmp_path / "ckpt" / f"worker_{w}_mom.npz") as z:
            assert int(z["step"]) == 30
            assert np.isfinite(z["mom"]).all() and np.any(z["mom"] != 0)

    # Fresh ports for the second generation of processes. Workers get
    # --resume too: the EMA restore is gated on it (a NON-resume run with a
    # stale checkpoint_dir must not silently load old momenta).
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    out, wouts = run(("--resume",), extra_w=("--resume",))
    assert "resumed from step 30" in out
    for w, wout in enumerate(wouts):
        assert "restored momentum EMA from step 30" in wout, (
            f"worker {w} did not restore its EMA:\n{wout[-800:]}"
        )
    summary = json.loads(
        [l for l in out.splitlines() if l.startswith("{")][-1]
    )
    assert summary["steps"] == 60


def test_worker_crash_survivors_converge(tmp_path):
    n_w = 4
    cfg_path, env = _cluster_setup(tmp_path, n_w)
    ps = _launch("ps:0", cfg_path, env)
    workers = [_launch(f"worker:{w}", cfg_path, env) for w in range(n_w)]
    victim = workers[-1]
    # Watchdog: the stdout readline loop below blocks on a silent-but-alive
    # PS, so bound that phase from a side thread; cancelled as soon as the
    # loop is past (the later waits are all timeout-bounded and must not
    # race a stray kill).
    watchdog = threading.Timer(
        420, lambda: [p.kill() for p in [ps, *workers]]
    )
    watchdog.start()
    try:
        # Wait for training to be demonstrably under way (the step-10
        # accuracy line), then SIGKILL one worker — a hard crash, not an
        # orderly close.
        first_acc = None
        deadline = time.time() + 240
        for line in ps.stdout:
            if line.startswith("Step: 0 "):
                first_acc = float(line.split()[3])
            if line.startswith("Step: 10 "):
                victim.send_signal(signal.SIGKILL)
                break
            if time.time() > deadline:
                pytest.fail("PS never reached step 10")
        else:
            pytest.fail(f"PS exited early: rc={ps.wait()}")
        watchdog.cancel()

        rest = ps.stdout.read()
        assert ps.wait(timeout=240) == 0, f"PS failed:\n{rest[-2000:]}"
        summary = json.loads(
            [l for l in rest.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 60
        # The surrogate task is separable: 60 post-crash-tolerant steps must
        # show real learning, not just survival.
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1)

        for w in workers[:-1]:  # survivors run to the end, rc 0
            out, _ = w.communicate(timeout=240)
            assert w.returncode == 0, f"survivor failed:\n{out[-2000:]}"
            wsum = json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            )
            # Catch-up semantics may skip a round under CPU load; a
            # survivor still contributes nearly every step.
            assert wsum["steps"] >= 50
        assert victim.wait(timeout=60) == -signal.SIGKILL
    finally:
        watchdog.cancel()
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()

"""LEARN topology: fully decentralized Byzantine-resilient collaborative
learning (every node is Worker + Server).

TPU-native re-design of ``pytorch_impl/applications/LEARN/trainer.py``
(node loop :224-257, ``avg_agree`` gossip :208-222): n peer nodes each hold
their own model and data shard; per step each node

    1. computes its own gradient                       (trainer.py:233-236)
    2. gathers everyone's gradients and aggregates     (:237-241)
    3. (non-iid) repeats ceil(log2 t) "agreement" rounds, re-gathering the
       peers' *aggregated* gradients and re-aggregating (:208-222, :251-252)
    4. applies its optimizer                            (:247-249)
    5. gossips models: gathers peer models, GAR-aggregates, writes back
                                                        (:255-257)

SPMD mapping (SURVEY §2.3 "Decentralized P2P" row): one "nodes" mesh axis;
model/optimizer state is stacked over it; every get_aggr_grads/get_models RPC
poll (server.py:202-233) becomes one all_gather. Byzantine nodes inject
gradient attacks (byzWorker.py) in phases 1-3 and model attacks
(byzServer.py) in phase 5 — value transforms on their rows of the gathered
stacks.

The ceil(log2 t) round count is data-dependent on the step counter, so the
gossip loop is a ``lax.fori_loop`` over a static ``max_rounds`` with rounds
beyond the target masked to no-ops (XLA needs static trip structure).
"""

import functools
import math

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from ..attacks import apply_gradient_attack, apply_model_attack
from . import core, mesh as mesh_lib
from .aggregathor import _check_gar, _resolve_gar

__all__ = ["make_trainer"]


def make_trainer(
    module,
    loss_fn,
    optimizer,
    gar,
    *,
    num_nodes,
    f=0,
    attack=None,
    attack_params=None,
    model_attack=None,
    model_attack_params=None,
    byz_mask=None,
    mesh=None,
    axis="nodes",
    non_iid=False,
    max_rounds=12,
    model_gossip=True,
):
    """Build ``(init_fn, step_fn, eval_fn)`` for the LEARN topology.

    ``non_iid=True`` enables the ceil(log2 t) agreement rounds
    (LEARN/trainer.py:251-252 runs them only for non-iid data); ``max_rounds``
    caps them (2^12 = 4096 steps of exact parity by default).
    ``step_fn(state, x, y)``: leading ``num_nodes`` axis on x/y and on every
    params/opt_state leaf, all sharded over ``axis``.
    """
    gar = _resolve_gar(gar)
    attack_params = dict(attack_params or {})
    model_attack_params = dict(model_attack_params or {})
    if mesh is None:
        mesh = mesh_lib.make_mesh({axis: -1})
    per_n = mesh_lib.fold(num_nodes, mesh.shape[axis], "nodes")
    _check_gar(gar, num_nodes, f)
    if byz_mask is None:
        byz_mask = core.default_byz_mask(
            num_nodes, f if (attack or model_attack) else 0
        )
    byz_mask = jnp.asarray(byz_mask, bool)

    init_worker, grad_fn, eval_apply = core.make_worker_fns(module, loss_fn)
    node_sharding = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def init_fn(key, example_x, seed_rng=None):
        params, model_state = init_worker(key, example_x)
        opt_state = optimizer.init(params)
        stack = lambda tree: jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (num_nodes,) + l.shape), tree
        )
        return core.TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), repl),
            params=jax.device_put(stack(params), node_sharding),
            model_state=jax.device_put(model_state, repl),
            opt_state=jax.device_put(stack(opt_state), node_sharding),
            rng=jax.device_put(key if seed_rng is None else seed_rng, repl),
        )

    def _local_step(state, x_local, y_local):
        base = jax.random.fold_in(state.rng, state.step)
        atk_key, gossip_key, matk_key, drop_base = jax.random.split(base, 4)
        shard = jax.lax.axis_index(axis)
        node_ids = shard * per_n + jnp.arange(per_n)

        # Phase 1: per-node gradient on its own model + batch (unrolled over
        # the static local slots; vmapping params over nodes trips conv
        # batching rules).
        grads, losses, ms_list = [], [], []
        for k in range(per_n):
            p_k = jax.tree.map(lambda l: l[k], state.params)
            rng_k = jax.random.fold_in(drop_base, node_ids[k])
            g, (loss, ms_out) = grad_fn(
                p_k, state.model_state, x_local[k], y_local[k], rng_k
            )
            grads.append(ravel_pytree(g)[0])
            losses.append(loss)
            ms_list.append(ms_out)
        flat_local = jnp.stack(grads)  # (per_n, d)
        losses = jnp.stack(losses)
        new_ms = core.mean_model_state(
            jax.tree.map(lambda *ls: jnp.stack(ls), *ms_list), axis
        )

        # Phase 2: gather + attack + aggregate (= get_gradients of every peer).
        stack0 = jax.lax.all_gather(flat_local, axis, tiled=True)  # (n, d)
        stack0 = apply_gradient_attack(
            attack, stack0, byz_mask, key=atk_key, **attack_params
        )
        aggr = gar.unchecked(stack0, f=f)  # identical on all honest nodes

        # Phase 3: avg_agree rounds (ceil(log2 t), LEARN/trainer.py:208-222).
        if non_iid:
            t = jnp.maximum(state.step, 1).astype(jnp.float32)
            rounds = jnp.ceil(jnp.log2(jnp.maximum(t, 2.0))).astype(jnp.int32)
            rounds = jnp.minimum(rounds, max_rounds)

            def round_body(r, aggr):
                # Every round: each node publishes its current aggregate; the
                # Byzantine rows are poisoned; re-aggregate.
                served = jnp.broadcast_to(aggr[None], stack0.shape)
                rkey = jax.random.fold_in(gossip_key, r)
                served = apply_gradient_attack(
                    attack, served, byz_mask, key=rkey, **attack_params
                )
                new = gar.unchecked(served, f=f)
                return jnp.where(r < rounds, new, aggr)

            aggr = jax.lax.fori_loop(0, max_rounds, round_body, aggr)

        # Phase 4: per-node optimizer step.
        new_params_list, new_opt_list = [], []
        for k in range(per_n):
            p_k = jax.tree.map(lambda l: l[k], state.params)
            o_k = jax.tree.map(lambda l: l[k], state.opt_state)
            updates, o_k = optimizer.update(
                core.unflatten_like(p_k, aggr), o_k, p_k
            )
            new_params_list.append(optax.apply_updates(p_k, updates))
            new_opt_list.append(o_k)
        new_params = jax.tree.map(lambda *ls: jnp.stack(ls), *new_params_list)
        new_opt = jax.tree.map(lambda *ls: jnp.stack(ls), *new_opt_list)

        # Phase 5: model gossip (LEARN/trainer.py:255-257).
        if model_gossip:
            flat_models = core.flatten_rows(new_params)  # (per_n, d)
            models = jax.lax.all_gather(flat_models, axis, tiled=True)
            poisoned = jax.vmap(
                lambda i, m: apply_model_attack(
                    model_attack, m, key=jax.random.fold_in(matk_key, i),
                    **model_attack_params,
                )
            )(jnp.arange(num_nodes), models)
            models = jnp.where(byz_mask[:, None], poisoned, models)
            aggr_model = gar.unchecked(models, f=f)
            written = core.unflatten_like(
                jax.tree.map(lambda l: l[0], new_params), aggr_model
            )
            new_params = jax.tree.map(
                lambda l: jnp.broadcast_to(l[None], (per_n,) + l.shape),
                written,
            )

        honest = (~byz_mask).astype(losses.dtype)[node_ids]
        loss_num = jax.lax.psum(jnp.sum(losses * honest), axis)
        loss_den = jax.lax.psum(jnp.sum(honest), axis)
        mean_loss = loss_num / jnp.maximum(loss_den, 1.0)

        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                model_state=new_ms,
                opt_state=new_opt,
            ),
            {"loss": mean_loss},
        )

    state_specs = core.TrainState(
        step=P(), params=P(axis), model_state=P(), opt_state=P(axis), rng=P()
    )
    sharded_step = jax.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(axis), P(axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, x, y):
        return sharded_step(state, x, y)

    @jax.jit
    def eval_fn(state, x):
        params0 = jax.tree.map(lambda l: l[0], state.params)
        return eval_apply(params0, state.model_state, x)

    step_fn.mesh = mesh
    step_fn.batch_sharding = node_sharding
    return init_fn, step_fn, eval_fn

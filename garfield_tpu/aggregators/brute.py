"""Brute GAR: minimum-diameter subset selection (optimal, exponential).

Counterpart of pytorch_impl/libs/aggregators/brute.py (:32-68): enumerate all
C(n, n-f) subsets of size n-f, pick the one with the smallest diameter (max
pairwise Euclidean distance; any subset containing a non-finite pair is
dropped), and average it. Requires n >= 2f+1 (:104).

TPU design: the combination table is enumerated once at trace time (n, f are
static) into an index tensor, the distance matrix is one Gram matmul, and the
per-subset diameter is a batched gather + max — fully vectorized, no Python
loop at run time (the reference's native version enumerates on a CPU
threadpool, py_brute/brute.cpp + combinations.hpp).
"""

import functools
import itertools

import jax.numpy as jnp
import numpy as np

from . import register
from ._common import as_stack, num_gradients, pairwise_distances

# Enumeration guard: C(n, n-f) combinations are materialized as one index
# tensor; keep the same practical bound the reference applies to its brute
# sweeps (benchmarks/gar_bench.py bounds n for brute).
MAX_COMBINATIONS = 2_000_000


@functools.lru_cache(maxsize=64)
def _combination_table(n, f):
    combos = np.array(
        list(itertools.combinations(range(n), n - f)), dtype=np.int32
    )
    return combos  # (C, n-f)


def _min_diameter_subset(dist, n, f):
    """(n-f,) indices of the minimum-diameter subset — the single source
    of the selection math (flat, tree, Gram-form, and influence paths all
    route here, so their trajectory equality cannot silently drift)."""
    combos = _combination_table(n, f)
    # (C, k, k) pairwise distances inside each candidate subset.
    sub = dist[combos[:, :, None], combos[:, None, :]]
    diam = jnp.max(sub, axis=(1, 2))  # inf iff subset holds a non-finite pair
    return jnp.asarray(combos)[jnp.argmin(diam)]


def _selection_weights_from_dist(dist, n, f):
    """1/(n-f) one-hot weights over the minimum-diameter subset."""
    sel = _min_diameter_subset(dist, n, f)
    return jnp.zeros((n,), jnp.float32).at[sel].set(1.0 / (n - f))


def selection_indices(gradients, f):
    """Index set (n-f,) of the minimum-diameter subset."""
    g = as_stack(gradients)
    return _min_diameter_subset(
        pairwise_distances(g, exclude_self=False), g.shape[0], f
    )


def aggregate(gradients, f, **kwargs):
    """Average of the minimum-diameter subset of size n-f.

    Masked matvec instead of ``mean(g[sel])`` — the same zero-guarded
    one-hot form as krum's (PERF.md: fuses, and 0 * inf stays 0)."""
    g = as_stack(gradients)
    n = g.shape[0]
    w = _selection_weights_from_dist(
        pairwise_distances(g, exclude_self=False), n, f
    ).astype(g.dtype)
    gz = jnp.where((w != 0)[:, None], g, 0)
    return w @ gz


def tree_aggregate(grads_tree, f, **kwargs):
    """Tree-mode brute: the min-diameter selection needs only pairwise
    distances, i.e. the summed per-leaf Gram (krum's trick — the
    reference's own selection is pure pairwise-distance, brute.py:32-68);
    the average is one per-leaf weighted row sum."""
    import jax

    from ._common import distances_from_gram, tree_gram, tree_weighted_sum

    leaves = jax.tree.leaves(grads_tree)
    n = leaves[0].shape[0]
    dist = distances_from_gram(tree_gram(grads_tree), exclude_self=False)
    return tree_weighted_sum(
        grads_tree, _selection_weights_from_dist(dist, n, f)
    )


def gram_select(gram, f, **kwargs):
    """Gram-form selection weights (parallel.fold): the folded-attack path
    remaps THIS matrix instead of writing poisoned rows."""
    from ._common import distances_from_gram

    n = gram.shape[0]
    return _selection_weights_from_dist(
        distances_from_gram(gram, exclude_self=False), n, f
    )


def check(gradients, f, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 1) // 2}"
        )
    import math

    if math.comb(n, n - f) > MAX_COMBINATIONS:
        return (
            f"brute enumeration C({n}, {n - f}) = {math.comb(n, n - f)} exceeds "
            f"the practical bound {MAX_COMBINATIONS}"
        )
    return None


def upper_bound(n, f, d):
    """Variance/norm bound (n-f)/(2f) (brute.py:107-116)."""
    return (n - f) / (2 * f)


def influence(honests, attacks, f, **kwargs):
    """Ratio of Byzantine gradients in the selected subset (brute.py:119-139)."""
    stack = jnp.concatenate([as_stack(honests), as_stack(attacks)], axis=0)
    sel = np.asarray(selection_indices(stack, f))
    return float(np.sum(sel >= len(honests))) / (stack.shape[0] - f)


register("brute", aggregate, check, upper_bound=upper_bound,
         influence=influence, tree_aggregate=tree_aggregate,
         gram_select=gram_select)

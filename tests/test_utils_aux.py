"""Tests for aux subsystems: multihost config/faults, profiling accounting,
checkpointing, and the microbenchmark harnesses (SURVEY §5 parity)."""

import json
import os

import numpy as np
import pytest

from garfield_tpu.utils import checkpoint, multihost, profiling


def test_cluster_config_roundtrip(tmp_path):
    path = tmp_path / "cluster.json"
    multihost.generate_config(
        path, workers=["h1:2222", "h2:2222"], ps=["h0:2222"],
        task_type="worker", task_index=1, gar="krum", fw=1,
    )
    cfg = multihost.ClusterConfig(path)
    assert cfg.hosts == ["h0:2222", "h1:2222", "h2:2222"]
    assert cfg.coordinator == "h0:2222"
    assert cfg.num_processes == 3
    # ps ranks come first (reference convention, trainer.py:217)
    assert cfg.process_id == 2
    assert cfg.garfield == {"gar": "krum", "fw": 1}


def test_cluster_config_from_env_inline(monkeypatch):
    spec = {"cluster": {"worker": ["a:1", "b:1"]},
            "task": {"type": "worker", "index": 0}}
    monkeypatch.setenv("GARFIELD_CONFIG", json.dumps(spec))
    cfg = multihost.ClusterConfig.from_env()
    assert cfg.process_id == 0 and cfg.num_processes == 2


def test_init_distributed_single_process_noop():
    assert multihost.init_distributed(config=None) == (1, 0)


def test_fault_schedule_crash_and_straggler():
    sched = multihost.FaultSchedule(
        4, crashes={2: 10}, stragglers={1: 1.0}, seed=7
    )
    # Before the crash step host 2 is alive.
    assert not sched.byz_mask(5, 8).any()
    m = sched.byz_mask(10, 8)
    assert m.tolist() == [False] * 4 + [True, True] + [False] * 2
    # Straggler host 1 always suspected: q = n-1, floored at n-f.
    assert sched.subset(3, 8, f=2) == 7
    assert sched.subset(3, 8, f=0) == 8
    # Replayable.
    assert sched.subset(3, 8, 2) == sched.subset(3, 8, 2)


def test_collective_bytes_topologies():
    kw = dict(num_workers=8, d=1000, bytes_per_el=4)
    assert profiling.collective_bytes("centralized", **kw) == 0
    agg = profiling.collective_bytes("aggregathor", **kw)
    assert agg == int(8 * 1000 * 4 * 7 / 8)
    byz = profiling.collective_bytes("byzsgd", num_ps=3, **kw)
    assert byz > agg
    # One device: no inter-chip traffic at all.
    assert profiling.collective_bytes("aggregathor", axis_size=1, **kw) == 0


def test_step_timer():
    t = profiling.StepTimer()
    with t.step():
        pass
    s = t.summary()
    assert s["count"] == 1 and s["total_s"] >= 0


def test_checkpointer_pickle_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(checkpoint, "_HAVE_ORBAX", False)
    ck = checkpoint.Checkpointer(tmp_path / "ck", max_to_keep=2)
    state = {"w": np.arange(3.0), "step": np.int32(5)}
    for s in (1, 2, 3):
        ck.save(s, state)
    assert ck.latest_step() == 3
    assert ck._pickle_steps() == [2, 3]  # bounded history
    out = ck.restore(state)
    np.testing.assert_array_equal(out["w"], state["w"])


def test_evalset_matches_list_path():
    """parallel.EvalSet (one scanned program) must count exactly like the
    per-batch list path — uniform batches, a ragged tail, and the binary
    threshold path."""
    import jax
    import jax.numpy as jnp

    from garfield_tpu import parallel

    rng = np.random.default_rng(0)

    def eval_fn(state, x):
        return jnp.asarray(x) @ state  # logits = x @ W

    # Multiclass with a ragged tail batch (like pima's 100+68 test split).
    state = jnp.asarray(rng.standard_normal((5, 3)), jnp.float32)
    batches = [
        (rng.standard_normal((4, 5)).astype(np.float32),
         rng.integers(0, 3, 4))
        for _ in range(3)
    ] + [(rng.standard_normal((2, 5)).astype(np.float32),
          rng.integers(0, 3, 2))]
    want = parallel.compute_accuracy(state, eval_fn, batches)
    got = parallel.compute_accuracy(
        state, eval_fn, parallel.EvalSet(batches)
    )
    assert got == want

    # Binary path: single sigmoid-like output, labels (n, 1) float.
    bstate = jnp.asarray(rng.standard_normal((5, 1)), jnp.float32)

    def beval(state, x):
        return jax.nn.sigmoid(jnp.asarray(x) @ state)

    bbatches = [
        (rng.standard_normal((4, 5)).astype(np.float32),
         rng.integers(0, 2, (4, 1)).astype(np.float32))
        for _ in range(2)
    ]
    want_b = parallel.compute_accuracy(bstate, beval, bbatches, binary=True)
    got_b = parallel.compute_accuracy(
        bstate, beval, parallel.EvalSet(bbatches, binary=True)
    )
    assert got_b == want_b

    # ADVICE r2: empty test_batches must raise a clear error, not an
    # opaque jnp.stack failure.
    with pytest.raises(ValueError, match="at least one test batch"):
        parallel.EvalSet([])


def test_gar_bench_smoke():
    from garfield_tpu.apps.benchmarks import gar_bench

    rows = gar_bench.main(
        ["--gars", "median", "krum", "--ns", "8", "--ds", "10", "--reps", "2"]
    )
    assert {r["gar"] for r in rows} == {"median", "krum"}
    # latency is a positive float, or None with the below_noise_floor flag
    # (tiny d on a fast backend legitimately sits under the paired-reps
    # noise floor).
    for r in rows:
        if r["latency_s"] is None:
            assert r.get("below_noise_floor") is True
        else:
            assert r["latency_s"] > 0


def test_transfer_bench_smoke(tmp_path):
    from garfield_tpu.apps.benchmarks import transfer_bench
    from garfield_tpu.telemetry.exporters import validate_jsonl

    out = tmp_path / "transfer.json"
    rows = transfer_bench.main([
        "--ds", "100", "--reps", "2", "--trials", "2", "--json", str(out),
    ])
    assert rows
    for r in rows:  # below-noise rows carry no gbit_per_s
        if r["latency_s"] is None:
            assert r.get("below_noise_floor") is True
        else:
            assert r["gbit_per_s"] > 0
        assert r["trials"] == 2  # min-over-k provenance (gar_bench parity)
    # Schema-versioned JSONL twin rides --json (gar_bench r7 parity).
    assert validate_jsonl(tmp_path / "transfer.jsonl") == len(rows)


def test_multihost_config_cli(tmp_path):
    """Flag-driven config generator writes one valid per-task JSON per host
    (reference config_generator.py parity)."""
    from garfield_tpu.utils import multihost

    files = multihost._cli([
        str(tmp_path), "--workers", "h1:9901", "h2:9901", "h3:9901",
        "--ps", "h0:9901", "--gar", "krum", "--fw", "1", "--attack", "lie",
    ])
    assert len(files) == 4
    for i, f in enumerate(files):
        cfg = multihost.ClusterConfig(f)
        assert cfg.num_processes == 4
        assert cfg.coordinator == "h0:9901"
        assert cfg.garfield["gar"] == "krum"
        assert cfg.process_id == i  # ps first, then workers, stable order


def test_multihost_config_cli_validation(tmp_path):
    from garfield_tpu.utils import multihost

    with pytest.raises(SystemExit):  # no workers
        multihost._cli([str(tmp_path), "--workers"])
    with pytest.raises(SystemExit):  # fw budget too big
        multihost._cli([str(tmp_path), "--workers", "h1", "h2", "--fw", "1"])
    with pytest.raises(SystemExit):  # fps without ps hosts
        multihost._cli([str(tmp_path), "--workers", "h1", "h2", "h3",
                        "--fps", "1"])

"""(Multi-)Krum GAR.

Counterpart of pytorch_impl/libs/aggregators/krum.py: score of node i = sum
of its n-f-1 smallest Euclidean distances to the other nodes (:31-63), and
Multi-Krum averages the m best-scored gradients with default m = n-f-2
(:65-80). Selection requires n >= 2f+3 (:98-113).

TPU design: the O(n^2) distance matrix is one Gram matmul on the MXU
(replacing the reference's CUDA per-pair reduction kernels, py_krum/krum.cu);
score + selection are a row-sort and a stable argsort — all fused by XLA
inside the surrounding jit'd train step.
"""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import register
from ._common import (
    as_stack,
    distances_from_gram,
    num_gradients,
    pairwise_distances,
    tree_gram,
    tree_weighted_sum,
)
from ..ops import coordinate as _coord


def _sortnet_select(use_sortnet=None):
    """Whether the fast selection path is on: explicit override wins, else
    the ``GARFIELD_SORTNET_SELECT`` knob (default ON). Read at TRACE time —
    callers that bench both paths (gar_bench --selection) must pass the
    override explicitly so each impl gets its own jit closure instead of
    poisoning a shared cache with an env read."""
    if use_sortnet is not None:
        return bool(use_sortnet)
    return os.environ.get("GARFIELD_SORTNET_SELECT", "1").lower() not in (
        "", "0", "false",
    )


def _scores_from_dist(dist, n, f, use_sortnet=None):
    """Krum score of row i = sum of its n-f-1 smallest distances to the
    other rows (krum.py:55-63). The single source of the score formula —
    the flat path, the tree path, and selection_indices all go through it,
    so the trajectory-equality the tests assert cannot silently break.

    Fast path (GARFIELD_SORTNET_SELECT, default on): the full row sort is
    never materialized. n <= MAX_SORT_N runs the odd-even network's
    k-smallest-sum (``sortnet_row_sums`` — one batched network under the
    hierarchy's vmapped wave instead of per-bucket XLA variadic sorts);
    larger n reduces via negated ``lax.top_k`` (negation is exact; dist
    has no NaN — diag and non-finite entries are +inf). EVERY path sums
    its k ascending values as an explicit add chain: a chain's order is
    fixed (XLA never reassociates float adds) where an axis ``jnp.sum``
    may regroup per fusion context, so the on/off paths see identical
    operands in identical order — same scores bitwise, the trajectory pin
    tests/test_gars.py asserts.
    """
    k = n - f - 1

    def _chain(cols):
        acc = cols[0]
        for i in range(1, k):
            acc = acc + cols[i]
        return acc

    if _sortnet_select(use_sortnet):
        if n <= _coord.MAX_SORT_N:
            return _coord.sortnet_row_sums(dist, k, axis=1)
        neg, _ = jax.lax.top_k(-dist, k)  # k smallest, ascending after -
        return _chain([-neg[:, i] for i in range(k)])
    sorted_d = jnp.sort(dist, axis=1)
    return _chain([sorted_d[:, i] for i in range(k)])


def _selection_weights_from_dist(dist, n, f, m, use_sortnet=None):
    """One-hot/m weight vector over the m best-scored rows (stable ties) —
    the masked matvec form of ``mean(g[sel])`` (see ``aggregate``). On the
    fast path at n <= MAX_SORT_N the m best indices come from the
    index-carrying network (``sortnet_top_m``), which reproduces the
    stable-argsort prefix bitwise (strict-< network: ties keep ascending
    index order); above the bound the stable argsort stays — ``top_k``'s
    tie order is not contractually stable, and flat n > 32 selection is
    off the critical path (the hierarchy folds buckets of <= 32)."""
    scores = _scores_from_dist(dist, n, f, use_sortnet)
    if _sortnet_select(use_sortnet) and n <= _coord.MAX_SORT_N:
        sel = _coord.sortnet_top_m(scores, m, axis=0)
    else:
        sel = jnp.argsort(scores)[:m]
    return jnp.zeros((n,), jnp.float32).at[sel].set(1.0 / m)


def selection_indices(gradients, f, m=None, use_sortnet=None):
    """Indices of the m best-scored gradients, best first (stable ties)."""
    g = as_stack(gradients)
    n = g.shape[0]
    if m is None:
        m = n - f - 2
    dist = pairwise_distances(g)  # (n, n), diag/non-finite -> +inf
    scores = _scores_from_dist(dist, n, f, use_sortnet)
    if _sortnet_select(use_sortnet) and n <= _coord.MAX_SORT_N:
        return _coord.sortnet_top_m(scores, m, axis=0)
    return jnp.argsort(scores)[:m]


def aggregate(gradients, f, m=None, use_sortnet=None, **kwargs):
    """Multi-Krum: average of the m best-scored gradients.

    The average is computed as a one-hot weight matvec ``w @ g`` rather than
    ``mean(g[sel])``: the dynamic gather materializes an (m, d) copy before
    reducing, while the masked matvec lets XLA fuse the zero-guard into the
    dot's operand read — measured ~1.5x faster at n=8/16, d=11.2M on a real
    chip (PERF.md).
    """
    g = as_stack(gradients)
    n = g.shape[0]
    if m is None:
        m = n - f - 2
    w = _selection_weights_from_dist(
        pairwise_distances(g), n, f, m, use_sortnet
    ).astype(g.dtype)
    # Zero-weight rows must not poison the matvec with NaN/Inf coordinates
    # (0 * inf = nan); selected rows pass through untouched, preserving the
    # reference's mean(g[sel]) semantics exactly.
    gz = jnp.where((w != 0)[:, None], g, 0)
    return w @ gz


def tree_aggregate(grads_tree, f, m=None, use_sortnet=None, **kwargs):
    """Tree-mode Multi-Krum: no (n, d) flat stack.

    The pairwise distances need only the Gram matrix, which is the sum of
    per-leaf Grams (``_common.tree_gram``); the selection average is a
    per-leaf weighted row sum. Saves the flatten + unflatten round trip —
    ~5 ms/step at ResNet-18 scale on one chip (PERF.md).
    """
    leaves = jax.tree.leaves(grads_tree)
    n = leaves[0].shape[0]
    if m is None:
        m = n - f - 2
    dist = distances_from_gram(tree_gram(grads_tree))
    w = _selection_weights_from_dist(dist, n, f, m, use_sortnet)
    return tree_weighted_sum(grads_tree, w)


def gram_select(gram, f, m=None, use_sortnet=None, **kwargs):
    """Selection weights from a (possibly attack-remapped) Gram matrix —
    the Gram-form interface behind the folded attack path (parallel.fold):
    ``aggregate(stack) == gram_select(stack @ stack.T) @ stack``. Under the
    hierarchy's vmapped wave fold this is where the batched selection
    network lands: one network over the whole (W, s, s) wave instead of W
    per-bucket XLA sorts."""
    n = gram.shape[0]
    if m is None:
        m = n - f - 2
    return _selection_weights_from_dist(
        distances_from_gram(gram), n, f, m, use_sortnet
    )


def check(gradients, f, m=None, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 3:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 3) // 2}"
        )
    if m is not None and (not isinstance(m, int) or m < 1 or m > n - f - 2):
        return (
            f"invalid number of selected gradients, got m = {m!r}, "
            f"expected 1 <= m <= {n - f - 2}"
        )
    return None


def upper_bound(n, f, d):
    """Variance/norm bound for (Multi-)Krum (krum.py:115-124)."""
    return 1 / math.sqrt(
        2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2))
    )


def influence(honests, attacks, f, m=None, **kwargs):
    """Ratio of Byzantine gradients among the m selected (krum.py:126-150)."""
    stack = jnp.concatenate([as_stack(honests), as_stack(attacks)], axis=0)
    n = stack.shape[0]
    if m is None:
        m = n - f - 2
    sel = np.asarray(selection_indices(stack, f, m))
    return float(np.sum(sel >= len(honests))) / m


register("krum", aggregate, check, upper_bound=upper_bound,
         influence=influence, tree_aggregate=tree_aggregate,
         gram_select=gram_select)

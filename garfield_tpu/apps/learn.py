"""LEARN: fully decentralized Byzantine-resilient collaborative learning.

Counterpart of ``pytorch_impl/applications/LEARN/trainer.py`` (P19): every
node is Worker + Server (:224-231); per step each node aggregates everyone's
gradients, optionally runs ceil(log2 t) extra agreement rounds for non-iid
data (:208-222, :251-252), then gossips and GAR-aggregates models (:255-257).
``--num_workers`` is the node count (the reference demo calls it n).
``--subset`` enables the wait-n-f path: the reference's LEARN always waits
for only the n - f fastest peers (trainer.py:249, :255); pass
``--subset $((n - f))`` for exact protocol parity, or leave unset for full
participation.

  python -m garfield_tpu.apps.learn --dataset pima --model pimanet \\
      --loss bce --num_workers 8 --fw 1 --gar median \\
      --optimizer rmsprop --opt_args '{"lr":"0.001","momentum":"0.9","weight_decay":"0.0005"}'
"""

import sys

from ..parallel import learn
from . import common


def main(argv=None):
    parser = common.base_parser(
        "LEARN implementation using garfield-tpu", default_loss="bce"
    )
    parser.add_argument(
        "--non_iid", action="store_true",
        help="Enable the ceil(log2 t) agreement rounds "
             "(LEARN/trainer.py:251-252).",
    )
    parser.add_argument(
        "--model_attack", type=str, default=None,
        help="Byzantine model-gossip attack: random, reverse, drop.",
    )
    parser.add_argument(
        "--no_model_gossip", action="store_true",
        help="Disable the model gossip phase (LEARN/trainer.py:255-257).",
    )
    args = parser.parse_args(argv)
    assert args.fw * 2 < args.num_workers or args.fw == 0
    return common.train(
        args,
        topology=learn,
        make_trainer_kwargs=dict(
            num_nodes=args.num_workers,
            f=args.fw,
            attack=args.attack,
            attack_params=args.attack_params,
            model_attack=args.model_attack,
            non_iid=args.non_iid,
            model_gossip=not args.no_model_gossip,
            subset=args.subset,
        ),
        num_slots=args.num_workers,
        tag="learn",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

"""Data-plane defense (aggregators/dataplane.py, DESIGN.md §18).

Unit coverage of the fingerprint construction and both detectors
(dual-backend agreement, cohort sensitivity, clean-history identity),
the host ``DataPlaneDefense`` EMA/weight law, the in-graph deployment on
the SSMW step (backdoor cohort down-weighted; dp EMA rides the chunk
carry bitwise), the schema-v9 telemetry plumbing — and the PR-11 bitwise
pin: with the data-plane defense OFF, trajectories (defense off AND
GAR-defense-only) are bit-identical to the fixture captured before this
subsystem existed (tests/fixtures/dataplane_pin.json).
"""

import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models
from garfield_tpu.aggregators import dataplane as dp, defense as defense_lib
from garfield_tpu.parallel import aggregathor, core
from garfield_tpu.utils import selectors

_FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures",
    "dataplane_pin.json",
)

N, C, FH = 16, 1, 64


def _setup():
    module = models.select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.0)
    return module, loss, opt


def _batch_stack(seed=0, bsz=16, nb=3, slots=16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(slots, nb, bsz, 8)).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _cohort_heads(seed=0, n=N, f=3, coherent=True):
    """Synthetic head gradients: honest crowd around one direction, a
    Byzantine cohort coherently elsewhere with a shifted bias."""
    rng = np.random.default_rng(seed)
    base = rng.normal(size=(C, FH)).astype(np.float32)
    H = base[None] + 0.3 * rng.normal(size=(n, C, FH)).astype(np.float32)
    b = 0.3 * rng.normal(size=(n, C)).astype(np.float32)
    coh = rng.normal(size=(C, FH)).astype(np.float32)
    for i in range(n - f, n):
        jitter = 0.05 if coherent else 0.8
        H[i] = -0.8 * base + coh + jitter * rng.normal(
            size=(C, FH)
        ).astype(np.float32)
        b[i] = -2.0 + 0.05 * rng.normal(size=(C,)).astype(np.float32)
    return H, b


# --- fingerprints + detectors ------------------------------------------------


def test_head_spec_and_extraction_agree():
    """``head_spec`` + ``head_from_rows`` (the host wire path) must
    extract exactly what ``head_leaves`` reads off the stacked tree (the
    in-graph path) — the two deployments share one definition of 'the
    classifier head'."""
    module, loss, _ = _setup()
    init_fn, _, _ = core.make_worker_fns(module, loss)
    params, _ = init_fn(jax.random.PRNGKey(0), np.zeros((4, 8), np.float32))
    spec = dp.head_spec(params)
    assert spec is not None
    assert spec.classes == 1 and spec.feat == 64
    assert spec.bias is not None
    # A stacked "gradient" tree: n copies of params scaled per rank.
    stacked = jax.tree.map(
        lambda l: jnp.stack([l * (i + 1) for i in range(4)]), params
    )
    k_tree, b_tree = dp.head_leaves(stacked)
    assert k_tree.shape == (4, 1, 64) and b_tree.shape == (4, 1)
    rows = core.flatten_rows(stacked)
    k_rows, b_rows = dp.head_from_rows(spec, np.asarray(rows))
    np.testing.assert_allclose(np.asarray(k_tree), k_rows, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b_tree), b_rows, rtol=1e-6)


def test_transformer_head_resolution_vit():
    """ViT's head must resolve to the top-level Dense, NOT the
    ``pos_embedding`` table — flax flattens by sorted string key, so the
    lowercase positional param lands AFTER every capitalized module
    scope and the legacy "last 2-D leaf" rule would fingerprint it."""
    from garfield_tpu.models import transformer

    vit = transformer.ViT(dim=24, depth=2, heads=2, mlp_dim=48)
    p = vit.init(
        jax.random.PRNGKey(0), np.zeros((2, 16, 16, 3), np.float32)
    )["params"]
    spec = dp.head_spec(p)
    assert spec.feat == 24 and spec.classes == 10
    assert spec.bias is not None
    stacked = jax.tree.map(lambda l: jnp.stack([l, 2.0 * l]), p)
    k_tree, b_tree = dp.head_leaves(stacked)
    assert k_tree.shape == (2, 10, 24) and b_tree.shape == (2, 10)
    # Identity to the actual head params (class-major transpose), and
    # wire-path agreement with the in-graph extraction.
    np.testing.assert_allclose(
        np.asarray(k_tree[0]), np.asarray(p["Dense_0"]["kernel"]).T,
        rtol=1e-6,
    )
    rows = core.flatten_rows(stacked)
    k_rows, b_rows = dp.head_from_rows(spec, np.asarray(rows))
    np.testing.assert_allclose(np.asarray(k_tree), k_rows, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b_tree), b_rows, rtol=1e-6)


def test_transformer_head_resolution_gpt_untied():
    """Untied GPT: the top-level Dense head wins over both the nested
    ``EncoderBlock_*`` MLP kernels and the ``nn.Embed`` table."""
    from garfield_tpu.models import transformer

    gpt = transformer.GPT(vocab=16, dim=16, depth=1, heads=2, mlp_dim=32)
    p = gpt.init(
        jax.random.PRNGKey(0), np.zeros((2, 6), np.int32)
    )["params"]
    spec = dp.head_spec(p)
    assert spec.feat == 16 and spec.classes == 10
    assert spec.bias is not None
    stacked = jax.tree.map(lambda l: jnp.stack([l, -l]), p)
    k_tree, _ = dp.head_leaves(stacked)
    np.testing.assert_allclose(
        np.asarray(k_tree[0]), np.asarray(p["Dense_0"]["kernel"]).T,
        rtol=1e-6,
    )


def test_tied_gpt_head_refuses_loudly():
    """GPT(tied=True) has NO head distinct from the embedding gradient:
    both the host and the in-graph resolvers must refuse with a clear
    error instead of silently fingerprinting an interior MLP kernel."""
    from garfield_tpu.models import transformer

    gpt = transformer.GPT(
        vocab=16, dim=16, depth=1, heads=2, mlp_dim=32, tied=True
    )
    p = gpt.init(
        jax.random.PRNGKey(0), np.zeros((2, 6), np.int32)
    )["params"]
    with pytest.raises(ValueError, match="embedding-tied"):
        dp.head_spec(p)
    stacked = jax.tree.map(lambda l: jnp.stack([l, l]), p)
    with pytest.raises(ValueError, match="embedding-tied"):
        dp.head_leaves(stacked)


def test_suspect_class_robust_to_small_cohort():
    """At f/n = 1/4 a coherent cohort caps its own mean/std z at
    ~sqrt((n-f)/f) = 1.73 (it corrupts the mean and inflates the std of
    the class it attacks), so one noisy honest rank in a quiet class
    outscored the true target and steered the 2-means at clean rows.
    The median/MAD statistic must keep pointing at the target class."""
    rng = np.random.default_rng(0)
    kern = rng.normal(size=(8, 10, 4)).astype(np.float32)
    b = 0.05 * rng.normal(size=(8, 10)).astype(np.float32)
    b[6:, 3] = -0.9  # coherent 2-of-8 cohort on the target class
    b[1, 7] = 0.4  # one loud honest rank elsewhere
    assert int(dp.suspect_class(kern, b)) == 3
    assert int(dp.suspect_class(jnp.asarray(kern), jnp.asarray(b))) == 3


def test_detect_flags_small_cohort():
    """2-of-8 coherent target-class cohort — the realistic fine-tuning
    quorum shape the spectral tail alone cannot reach (its score is
    rms-normalized by a crowd the cohort itself inflates, bounded by
    sqrt(n/f) = 2.0 = tau): the cluster path must carry it."""
    rng = np.random.default_rng(1)
    H = 0.1 * rng.normal(size=(8, 10, 16)).astype(np.float32)
    b = 0.05 * rng.normal(size=(8, 10)).astype(np.float32)
    coh = rng.normal(size=(16,)).astype(np.float32)
    for i in (6, 7):
        H[i, 3] = 4.0 * coh + 0.02 * rng.normal(size=(16,))
        b[i, 3] = -0.9
    _, flags = dp.detect(H, b, f=2)
    assert flags[6:].all(), f"cohort not flagged: {flags}"
    assert not flags[:6].any(), f"honest ranks flagged: {flags}"


def test_detectors_flag_coherent_cohort_not_clean():
    H, b = _cohort_heads(seed=0, f=3)
    scores, flags = dp.detect(H, b, f=3)
    assert flags[-3:].all(), f"cohort not flagged: {flags}"
    assert not flags[:-3].any(), f"honest ranks flagged: {flags}"
    # Clean crowd: no flags (the detector identity the clean-accuracy
    # delta bar rests on).
    rng = np.random.default_rng(7)
    base = rng.normal(size=(C, FH)).astype(np.float32)
    H2 = base[None] + 0.3 * rng.normal(size=(N, C, FH)).astype(np.float32)
    b2 = 0.3 * rng.normal(size=(N, C)).astype(np.float32)
    _, flags2 = dp.detect(H2, b2, f=3)
    assert not flags2.any()


def test_detect_dual_backend_agrees():
    H, b = _cohort_heads(seed=3, f=3)
    s_np, f_np = dp.detect(H, b, f=3)
    s_j, f_j = dp.detect(jnp.asarray(H), jnp.asarray(b), f=3)
    np.testing.assert_allclose(np.asarray(s_j), s_np, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(f_j), f_np)


def test_cluster_flags_respect_f_budget_and_separation():
    rng = np.random.default_rng(1)
    # Tight cohort of 3 within f=3: flagged.
    rows = rng.normal(size=(12, FH)).astype(np.float32)
    rows[-3:] = rows[-1] + 0.01 * rng.normal(size=(3, FH)).astype(
        np.float32
    ) + 5.0
    flags = dp.cluster_flags(rows, f=3)
    assert flags[-3:].all() and not flags[:-3].any()
    # Same cohort, declared budget f=2: a 3-member cluster is larger
    # than the budget — NOT a cohort verdict.
    assert not dp.cluster_flags(rows, f=2).any()
    # No separation (one Gaussian blob): nothing flagged.
    blob = rng.normal(size=(12, FH)).astype(np.float32)
    assert not dp.cluster_flags(blob, f=3).any()


def test_fingerprints_scale_free():
    """Uniformly rescaling every rank's head gradient leaves the
    fingerprints unchanged up to float noise (the data plane keys on
    per-class structure, not magnitude — magnitude is the GAR's job)."""
    H, b = _cohort_heads(seed=5)
    f1 = dp.fingerprints(H, b)
    f2 = dp.fingerprints(10.0 * H, 10.0 * b)
    np.testing.assert_allclose(f1, f2, atol=1e-4)


# --- host DataPlaneDefense ---------------------------------------------------


def _spec_for_heads():
    """A HeadSpec over rows laid out as [bias | kernel] flat."""
    return dp.HeadSpec(
        kernel=(C, C + C * FH), bias=(0, C), feat=FH, classes=C
    )


def _flat_rows(H, b):
    n = H.shape[0]
    return np.concatenate(
        [b.reshape(n, -1),
         np.swapaxes(H, 1, 2).reshape(n, -1)], axis=1
    ).astype(np.float32)


def test_dataplane_defense_ema_and_weights():
    pdef = dp.DataPlaneDefense(
        N, _spec_for_heads(), f=3, halflife=4.0, floor=0.1
    )
    # Clean history: weights exactly 1.0 -> weights_for returns None
    # (the unweighted-program identity).
    rng = np.random.default_rng(2)
    base = rng.normal(size=(C, FH)).astype(np.float32)
    Hc = base[None] + 0.3 * rng.normal(size=(N, C, FH)).astype(np.float32)
    bc = 0.3 * rng.normal(size=(N, C)).astype(np.float32)
    for _ in range(3):
        pdef.observe(np.arange(N), _flat_rows(Hc, bc))
    assert pdef.weights_for(np.arange(N)) is None
    # Cohort rounds: the flagged ranks' EMA suspicion drives their
    # weights to the floor; honest ranks stay at ~1.
    H, b = _cohort_heads(seed=11, f=3)
    for _ in range(12):
        pdef.observe(np.arange(N), _flat_rows(H, b))
    w = pdef.weights_full()
    assert (w[-3:] <= 0.11).all(), w
    assert (w[:-3] >= 0.9).all(), w
    stats = pdef.stats()
    assert stats["rounds"] == 15 and stats["flagged"] >= 30
    assert stats["min_w"] <= 0.11


def test_dataplane_defense_small_quorum_skips():
    pdef = dp.DataPlaneDefense(N, _spec_for_heads(), f=3)
    rep = pdef.observe([0, 1, 2], np.zeros((3, C + C * FH), np.float32))
    assert not rep["flags"].any() and (rep["scores"] == 0).all()


# --- in-graph deployment -----------------------------------------------------


def _data_trainer(defense, attack="backdoor"):
    module, loss, opt = _setup()
    return aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=16, f=3,
        attack=attack, attack_params={"source": 0, "target": 1},
        defense=defense,
    )


def test_ingraph_data_defense_downweights_backdoor_cohort():
    """The tentpole contract, on-mesh: under a backdoor cohort the dp
    weights pin the Byzantine slots at the floor within the EMA window
    while honest slots keep ~1.0 — the evidence the GAR-side suspicion
    plane structurally cannot produce (DEFBENCH_r02's open cell)."""
    init_fn, step_fn, _ = _data_trainer(
        {"weighted": False,
         "data": {"tau": 2.0, "floor": 0.1, "halflife": 8.0}}
    )
    xs, ys = _batch_stack()
    state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    for i in range(30):
        b = i % 3
        state, m = step_fn(state, xs[:, b], ys[:, b])
    w = np.asarray(m["dataplane_w"])
    assert (w[-3:] <= 0.2).all(), w
    assert (w[:-3] >= 0.8).all(), w
    flags = np.asarray(m["dataplane_flags"])
    assert flags[-3:].sum() >= 2, flags
    scores = np.asarray(m["dataplane_score"])
    assert scores.shape == (16,) and np.isfinite(scores).all()


def test_ingraph_data_defense_chunked_bitwise():
    """The dp EMA twins ride TrainState.defense_state: a chunked scan
    must carry them bitwise like every other state leaf."""
    init_fn, step_fn, _ = _data_trainer(
        {"weighted": False, "data": {"halflife": 8.0}}
    )
    xs, ys = _batch_stack()
    state0 = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    ref, ref_m = state0, []
    for i in range(6):
        ref, m = step_fn(ref, xs[:, i % 3], ys[:, i % 3])
        ref_m.append(jax.device_get(m))
    chunked = core.make_chunked_step(step_fn, 3, 3)
    got, got_m = state0, []
    for i in range(0, 6, 3):
        got, m = chunked(got, xs, ys, np.int32(i))
        got_m.append(jax.device_get(m))
    for a, bb in zip(jax.tree.leaves(jax.device_get(ref)),
                     jax.tree.leaves(jax.device_get(got))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))
    ref_stack = jax.tree.map(lambda *ls: np.stack(ls), *ref_m)
    got_stack = jax.tree.map(lambda *ls: np.concatenate(ls), *got_m)
    for a, bb in zip(jax.tree.leaves(ref_stack), jax.tree.leaves(got_stack)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))


def test_ingraph_data_composes_with_gar_defense():
    """escalate+data's in-graph half: GAR-suspicion weighting AND the
    data detectors in one step program, both weight vectors surfaced."""
    init_fn, step_fn, _ = _data_trainer(
        {"halflife": 16.0, "data": {"halflife": 8.0}}
    )
    xs, ys = _batch_stack()
    state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    for i in range(4):
        state, m = step_fn(state, xs[:, i % 3], ys[:, i % 3])
    assert "defense_w" in m and "dataplane_w" in m
    assert np.asarray(m["defense_w"]).shape == (16,)
    assert np.asarray(m["dataplane_w"]).shape == (16,)


def test_defense_validation():
    with pytest.raises(ValueError, match="neither"):
        _data_trainer({"weighted": False})
    with pytest.raises(ValueError, match="unknown defense.data"):
        _data_trainer({"data": {"bogus": 1}})
    with pytest.raises(ValueError, match="tau"):
        _data_trainer({"data": {"tau": -1.0}})


# --- the PR-11 bitwise pin ---------------------------------------------------


def test_dataplane_off_trajectories_bitwise_pinned():
    """Defense-off and GAR-defense-only trajectories must stay BIT-
    identical to the fixture captured at PR 11, before the data plane
    existed: nothing dataplane-shaped may be traced when it is off."""
    fixture = json.load(open(_FIXTURE))
    module, loss, opt = _setup()
    cases = {
        "backdoor-off": ("backdoor", None),
        "labelflip-off": ("labelflip", None),
        "backdoor-gardef": ("backdoor", {"halflife": 16.0}),
    }
    for name, (attack, defense) in cases.items():
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=16, f=3,
            attack=attack, attack_params={"source": 0, "target": 1},
            defense=defense,
        )
        xs, ys = _batch_stack()
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        losses = []
        for i in range(8):
            state, m = step_fn(state, xs[:, i % 3], ys[:, i % 3])
            losses.append(
                np.asarray(m["loss"], np.float32).tobytes().hex()
            )
        assert losses == fixture[name]["losses"], name
        flat = np.concatenate([
            np.asarray(l, np.float32).reshape(-1)
            for l in jax.tree.leaves(state.params)
        ])
        digest = hashlib.sha256(flat.tobytes()).hexdigest()
        assert digest == fixture[name]["params_sha256"], name


# --- schema-v9 telemetry plumbing --------------------------------------------


def test_data_defense_event_and_summary_validate():
    from garfield_tpu.telemetry import exporters as tele_fmt
    from garfield_tpu.telemetry import hub as hub_lib

    hub = hub_lib.MetricsHub(num_ranks=4)
    rec = hub.record_event(
        "data_defense", step=3, plane="gradient",
        ranks=[0, 1, 2, 3], scores=[0.5, 0.4, 0.3, 3.2],
        flags=[0, 0, 0, 1], weights=[1.0, 1.0, 1.0, 0.1],
    )
    tele_fmt.validate_record(rec)
    stats = hub.data_defense_stats()
    assert stats["rounds"] == 1 and stats["flagged"] == 1
    assert stats["max_score"] == 3.2 and stats["min_w"] == 0.1
    summary = hub.summary()
    tele_fmt.validate_record(summary)
    assert summary["data_defense"] == {
        "rounds": 1, "flagged": 1, "max_score": 3.2, "min_w": 0.1,
    }
    prom = tele_fmt.prometheus_text(hub)
    assert 'garfield_dataplane_outlier_score{rank="3"} 3.2' in prom
    assert "garfield_dataplane_flagged_total 1" in prom
    # Malformed: flags length mismatch fails loudly.
    bad = dict(rec)
    bad["flags"] = [1]
    with pytest.raises(ValueError):
        tele_fmt.validate_record(bad)


def test_targeted_eval_reports_asr_baseline():
    """The clean-model trigger-rate baseline row (schema v9): the
    untriggered target-emission rate over non-target inputs, so ASR
    cells report attributable lift."""
    from garfield_tpu import parallel
    from garfield_tpu.attacks import targeted as targeted_lib
    from garfield_tpu.telemetry import exporters as tele_fmt

    module, loss, _ = _setup()
    init_fn, grad_fn, eval_apply = core.make_worker_fns(module, loss)
    rng = np.random.default_rng(0)
    xt = rng.normal(size=(40, 8)).astype(np.float32)
    yt = (xt.sum(-1) > 0).astype(np.float32)
    eval_set = parallel.EvalSet([(xt, yt)], binary=True)
    params, ms = init_fn(jax.random.PRNGKey(0), xt[:4])
    cfg = targeted_lib.TargetedConfig("backdoor", 0, 1, binary=True)
    rep = parallel.targeted_eval(
        (params, ms),
        lambda s, x: eval_apply(s[0], s[1], x),
        eval_set, source=0, target=1, trigger_cfg=cfg,
    )
    assert rep["asr_baseline"] is not None
    assert 0.0 <= rep["asr_baseline"] <= 1.0
    # An untrained model never saw the trigger: its triggered rate is
    # within noise of the untriggered baseline (the attributable-lift
    # rationale).
    assert abs(rep["asr"] - rep["asr_baseline"]) < 0.5
    rec = tele_fmt.make_record(
        "event", event="targeted_eval", source=0, target=1,
        asr=rep["asr"], asr_baseline=rep["asr_baseline"],
    )
    tele_fmt.validate_record(rec)


def test_poison_mask_step_folding():
    """fold_in(seed, step) poison masks: per-step variation at
    poison_frac < 1, static all-ones at 1.0 (the bitwise-compat leg),
    and host/traced twins each deterministic per (seed, step)."""
    from garfield_tpu.attacks import targeted as targeted_lib

    cfg = targeted_lib.TargetedConfig(
        "backdoor", 0, 1, poison_frac=0.5, binary=True
    )
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(16, 8)
    ).astype(np.float32))
    y = jnp.zeros((16, 1), jnp.float32)
    x0, _ = targeted_lib.poison_batch(cfg, x, y, seed=3, step=0)
    x0b, _ = targeted_lib.poison_batch(cfg, x, y, seed=3, step=0)
    x1, _ = targeted_lib.poison_batch(cfg, x, y, seed=3, step=1)
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(x0b))
    assert (np.asarray(x0) != np.asarray(x1)).any()
    # poison_frac 1.0: step-independent (all samples poisoned).
    cfg1 = targeted_lib.TargetedConfig(
        "backdoor", 0, 1, poison_frac=1.0, binary=True
    )
    xa, _ = targeted_lib.poison_batch(cfg1, x, y, seed=3, step=0)
    xb, _ = targeted_lib.poison_batch(cfg1, x, y, seed=3, step=7)
    np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))

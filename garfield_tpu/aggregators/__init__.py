"""Robust Gradient Aggregation Rule (GAR) registry.

TPU-native counterpart of pytorch_impl/libs/aggregators/__init__.py:
  - ``make_gar`` (:42-69) wraps each rule with a checked variant selected
    under ``__debug__``;
  - ``register`` (:71-86) lets each rule module self-register;
  - ``gars`` (:89) is the name -> rule mapping;
  - sibling rule modules are auto-imported (:91-97).

Every rule is a pure function of a stacked ``(n, d)`` gradient array (or a
reference-style list of 1-D vectors) and tolerance ``f``; rules are
jit-compatible with static ``n`` and ``f`` and run as XLA on TPU. The
``native-*`` variants (C++ CPU kernels via the garfield_tpu.native runtime,
mirroring the reference's pytorch_impl/libs/native/) register themselves when
the native toolchain is available.
"""

import importlib
import pkgutil

from ..utils import tools

__all__ = ["gars", "register", "GAR"]


class GAR:
    """A registered aggregation rule.

    Attributes mirror the reference wrapper (aggregators/__init__.py:63-67):
    ``unchecked`` (raw rule), ``checked`` (validates with ``check`` first),
    ``check``, ``upper_bound``, ``influence``. Calling the GAR dispatches to
    ``checked`` when ``__debug__`` else ``unchecked`` (:61).
    """

    def __init__(self, name, unchecked, check, upper_bound=None, influence=None,
                 tree_aggregate=None, gram_select=None, fold_aggregate=None,
                 tree_aggregate_ext=None, fold_flat_aggregate=None,
                 stateful_center=False):
        self.name = name
        self.unchecked = unchecked
        self.check = check
        # Optional fast path: aggregate a stacked gradient TREE (leading n
        # axis per leaf) without materializing the (n, d) flat stack —
        # Gram/matvec-structured rules (average, krum) use per-leaf Gram
        # sums; coordinate-wise rules (median, tmean) and cclip decompose
        # per leaf (_common.tree_coordinatewise). See parallel/
        # aggregathor.py for the dispatch and PERF.md for the measured
        # wins (flat stack ~5 ms/step; median step 21.3 -> 16.2 ms).
        self.tree_aggregate = tree_aggregate
        # Optional Gram-form selection: ``gram_select(gram, f, **params) ->
        # (n,) weights`` such that the aggregate equals ``w @ stack``. Rules
        # exposing it (krum, average) get the folded attack application
        # (attacks.plan_gradient_attack_fold / parallel.fold): deterministic
        # attacks become a static remap+scale of the Gram, the poisoned rows
        # are never written, and the raw Gram keeps fusing into the
        # backward epilogue (PERF.md round 4: 1.16x on krum+lie).
        self.gram_select = gram_select
        # Generalization for rules whose output is NOT one weighted row sum
        # (Bulyan): ``fold_aggregate(gram_p, apply_rows, f, **params)``
        # receives the poisoned Gram plus an ``apply_rows(W)`` closure that
        # materializes ``W @ poisoned_stack`` as a stacked tree for any
        # (r, n) weight matrix — phase-2-style reductions then run on it.
        self.fold_aggregate = fold_aggregate
        # Folded form for coordinate-wise rules (median, tmean):
        # ``tree_aggregate_ext(ext_tree, row_map, row_scale, **params)``
        # aggregates the EXTENDED stacked tree (raw rows + the attack's
        # shared fake row) under a STATIC row remap/scale — the Pallas
        # kernels apply the remap in-register (ops.coordinate_median's
        # row_map/row_scale), so the poisoned stack never materializes.
        self.tree_aggregate_ext = tree_aggregate_ext
        # Folded form for iterative row-value rules (cclip): ``
        # fold_flat_aggregate(ext_stack, row_map, row_scale, f, **params)``
        # receives the EXTENDED flat (rows, d) stack (raw rows + the
        # attack's shared fake row) and the static remap/scale; the rule's
        # per-iteration passes (radii, clipped-mean matvec) apply the remap
        # to row-level scalars, so the poisoned stack never materializes
        # (parallel/fold.py dispatch; returns the flat (d,) aggregate).
        self.fold_flat_aggregate = fold_flat_aggregate
        # True for rules that accept a ``center=`` carried across steps
        # (cclip): topologies thread the previous aggregate through
        # TrainState.gar_state as the next v_0 instead of paying a robust
        # init every step (the paper's own recipe; PERF.md r5).
        self.stateful_center = stateful_center

        def checked(gradients, *args, **kwargs):
            message = check(gradients, *args, **kwargs)
            if message is not None:
                raise AssertionError(
                    f"aggregation rule {name!r} cannot be used: {message}"
                )
            return unchecked(gradients, *args, **kwargs)

        self.checked = checked
        self.upper_bound = upper_bound
        self.influence = influence
        self._call = checked if __debug__ else unchecked

    def __call__(self, gradients, *args, **kwargs):
        return self._call(gradients, *args, **kwargs)

    def __repr__(self):
        return f"<GAR {self.name}>"


gars = {}


def register(name, unchecked, check, upper_bound=None, influence=None,
             tree_aggregate=None, gram_select=None, fold_aggregate=None,
             tree_aggregate_ext=None, fold_flat_aggregate=None,
             stateful_center=False):
    """Register an aggregation rule (reference __init__.py:71-86)."""
    if name in gars:
        tools.warning(f"GAR {name!r} already registered; overwriting")
    gar = GAR(name, unchecked, check, upper_bound=upper_bound,
              influence=influence, tree_aggregate=tree_aggregate,
              gram_select=gram_select, fold_aggregate=fold_aggregate,
              tree_aggregate_ext=tree_aggregate_ext,
              fold_flat_aggregate=fold_flat_aggregate,
              stateful_center=stateful_center)
    gars[name] = gar
    return gar


# Auto-import sibling rule modules so each self-registers (reference :91-97).
for _modinfo in pkgutil.iter_modules(__path__):
    if _modinfo.name.startswith("_"):
        continue
    importlib.import_module(f"{__name__}.{_modinfo.name}")

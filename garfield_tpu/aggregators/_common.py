"""Shared jit-friendly primitives for the GAR library.

These helpers encode the semantics that every reference rule builds on
(pytorch_impl/libs/aggregators/*.py):
  - pairwise Euclidean (non-squared) distances with non-finite values mapped
    to +inf (krum.py:44-48, bulyan.py, brute.py:33-36);
  - the *lower* coordinate-wise median — torch's ``median(dim=0)`` returns the
    lower of the two middle elements for even n, and sorts NaN last, which is
    what makes the reference's median "NaN-resilient" (median.py:39).

"Sum of the k smallest" selections (krum.py:55-63) appear rule-side as sorted
prefix sums; stable ``jnp.argsort`` reproduces the reference's stable
``list.sort`` tie-breaking.

All functions are pure and shape-polymorphic only in the static sense: n, d,
f must be Python ints at trace time (XLA static shapes).
"""

import jax
import jax.numpy as jnp


def as_stack(gradients):
    """Normalize input to a (n, d) stacked array.

    Accepts the reference-style list of 1-D vectors (krum.py aggregate takes
    ``gradients`` as a list) or an already-stacked 2-D array — the natural
    form after ``jax.lax.all_gather`` on the workers mesh axis.
    """
    if isinstance(gradients, (list, tuple)):
        return jnp.stack([jnp.asarray(g).reshape(-1) for g in gradients])
    g = jnp.asarray(gradients)
    if g.ndim != 2:
        raise ValueError(f"expected (n, d) gradient stack, got shape {g.shape}")
    return g


def num_gradients(gradients):
    """Static number of gradients n (leading dim / list length)."""
    if isinstance(gradients, (list, tuple)):
        return len(gradients)
    return int(gradients.shape[0])


def distances_from_gram(gram, *, exclude_self=True):
    """(n, n) Euclidean distances from a Gram matrix <g_i, g_j>.

    ||x-y||^2 = ||x||^2 + ||y||^2 - 2<x,y>; the squared norms are the Gram
    diagonal. Non-finite distances (a Byzantine gradient containing NaN/Inf
    poisons its whole row) become +inf, mirroring the reference's isfinite
    guard (krum.py:46-48). The diagonal is +inf when exclude_self (so
    "k smallest" never counts the self-distance), else 0.
    """
    # Per-pair SYMMETRIC distances, like the reference's (it computes each
    # unordered pair once and reads it for both directions): XLA's matmul
    # may accumulate gram[i, j] and gram[j, i] in different orders, and
    # the resulting 1-ulp asymmetry breaks STRUCTURAL score ties the
    # wrong way — e.g. Bulyan/Krum at m=1, where the two endpoints of the
    # globally-closest pair tie exactly and the stable lowest-index
    # tie-break must decide (caught by the paper-transcribed brute-force
    # oracle in tests/test_reference_parity.py).
    gram = 0.5 * (gram + gram.T)
    sq = jnp.diagonal(gram)
    d2 = sq[:, None] + sq[None, :] - 2.0 * gram
    dist = jnp.sqrt(jnp.maximum(d2, 0.0))
    dist = jnp.where(jnp.isfinite(dist), dist, jnp.inf)
    n = gram.shape[0]
    diag = jnp.inf if exclude_self else 0.0
    return jnp.where(jnp.eye(n, dtype=bool), diag, dist)


def pairwise_distances(g, *, exclude_self=True):
    """(n, n) Euclidean distance matrix via the Gram trick.

    The inner product rides the MXU instead of materializing (n, n, d)
    differences (see ``distances_from_gram``). The Gram is ACCUMULATED in
    at-least-float32 like ``tree_gram`` — under bf16 gradients the flat and
    tree paths must make the SAME selections — via
    ``preferred_element_type``, so the (n, d) operands stay in their input
    dtype (no f32 copy of the stack; bf16 in / f32 out is the MXU's native
    mode).
    """
    acc = jnp.promote_types(g.dtype, jnp.float32)
    return distances_from_gram(
        jnp.matmul(g, g.T, preferred_element_type=acc),
        exclude_self=exclude_self,
    )


def tree_gram(grads_tree):
    """(n, n) Gram matrix of a stacked gradient tree, summed over leaves.

    <g_i, g_j> over the flat concatenation equals the sum of per-leaf inner
    products, so the Gram of the virtual (n, d) stack is computed without
    ever materializing it — each leaf contributes one (n, size) MXU matmul.
    Accumulated in at-least-float32 regardless of leaf dtype (matching
    ``pairwise_distances`` so flat and tree selections agree under bf16),
    with the leaf operands kept in their input dtype.
    """
    leaves = jax.tree.leaves(grads_tree)
    n = leaves[0].shape[0]
    acc_dtype = jnp.promote_types(leaves[0].dtype, jnp.float32)
    total = jnp.zeros((n, n), acc_dtype)
    for leaf in leaves:
        x = leaf.reshape(n, -1)
        total = total + jnp.matmul(
            x, x.T, preferred_element_type=acc_dtype
        )
    return total


def tree_weighted_sum(grads_tree, w):
    """Per-leaf weighted sum of rows: the tree analog of ``w @ stack``.

    Zero-weight rows are masked out before the contraction so a NaN/Inf in
    an unselected (Byzantine) row cannot poison the result (0 * inf = nan)
    — same guard as the flat selection-average (krum.py docstring).
    """
    keep = (w != 0)

    def one(leaf):
        wl = w.astype(leaf.dtype)
        mask = keep.reshape((-1,) + (1,) * (leaf.ndim - 1))
        return jnp.tensordot(wl, jnp.where(mask, leaf, 0), axes=(0, 0))

    return jax.tree.map(one, grads_tree)


def tree_coordinatewise(fn, stacked_tree):
    """Apply a coordinate-wise ``(n, d) -> (d,)`` reducer per LEAF of a
    stacked gradient tree — the shared plumbing of the tree-mode twins
    (median, tmean, cclip's center init): coordinate-wise rules decompose
    per leaf, so the (n, d) flat stack never materializes (PERF.md:
    21.3 -> 16.2 ms/step for the median aggregathor step on the chip)."""
    leaves, treedef = jax.tree.flatten(stacked_tree)
    n = leaves[0].shape[0]
    return jax.tree.unflatten(treedef, [
        fn(l.reshape(n, -1)).reshape(l.shape[1:]) for l in leaves
    ])


def concat_stack(leaves):
    """(stack, shapes): ONE axis-1 concat of the reshaped stacked leaves.

    The concat-first layout for rules that want a flat (n, d) stack anyway
    (Bulyan's selection matmul + fused phase-2): measured cheaper than the
    flat path's vmapped ravel_pytree (PERF.md r4). ``shapes`` feeds
    ``unflatten_vec`` — single-sourced here so the tree and folded paths
    cannot drift."""
    n = leaves[0].shape[0]
    stack = jnp.concatenate([l.reshape(n, -1) for l in leaves], axis=1)
    return stack, [l.shape[1:] for l in leaves]


def unflatten_vec(vec, treedef, shapes):
    """Slice a flat (d,) vector back into a pytree with the given leaf
    ``shapes`` (leaf-order spans, the inverse of an axis-1 concat of
    reshaped leaves). Shared by tree-mode Bulyan and the folded path."""
    off, parts = 0, []
    for shape in shapes:
        sz = 1
        for s in shape:
            sz *= s
        parts.append(vec[off:off + sz].reshape(shape))
        off += sz
    return jax.tree.unflatten(treedef, parts)


def coordinate_median(g):
    """Lower coordinate-wise median of a (n, d) stack -> (d,).

    torch's ``stack(g).median(dim=0)[0]`` semantics (median.py:39): for even n
    the smaller middle element (index (n-1)//2 of the sorted column), and NaN
    values sort last so up to ceil(n/2)-1 NaN entries per coordinate do not
    contaminate the result. Dispatches to the Pallas TPU kernel
    (garfield_tpu.ops) on TPU; jnp sort elsewhere.
    """
    from .. import ops

    return ops.coordinate_median(g)



"""Slot-twin layer library: composable primitives for slot-fused models.

The slot-fused formulation (see ``models/slotfused.py`` for the design
provenance and measurements) computes per-worker ("per-slot") gradients by
running the model ONCE on the flat ``(slots * b)`` batch and making only
the parameter-cotangent contractions slot-resolved. r5 proved the idea on
two hand-written monolithic forwards (ResNet, Cifarnet); this module
factors the per-layer machinery out so a twin for a new model family is a
thin graph description over these primitives (the per-model assemblies and
the ``SLOTFUSED_MODELS`` registry live in ``slotfused.py``):

  - ``slot_conv``       — custom-vjp convolution: primal and dx run fused
    on the flat batch with the shared kernel (``w_st[0]``); only the dw
    rule is slot-resolved. Supports ``feature_group_count`` so the
    depthwise families (mobilenet/v2) fold too.
  - ``bn_train``        — per-slot BatchNorm statistics over the flat
    batch, flax-numerics-compatible (f32 stats, compute-dtype normalize).
  - ``layer_norm``      — per-example feature-axis statistics (flax
    ``nn.LayerNorm`` numerics: f32 fast-variance stats, compute-dtype
    normalize) with PER-SLOT scale/bias; the stats need no slot
    resolution — only the affine parameters are worker-resolved.
  - ``dense``           — slot-batched matmul head ('sbf,sfo->sbo').
  - ``seq_dense``       — the sequence-layout sibling: (slots*b, T, F)
    through a per-slot kernel via the same 'sbf,sfo->sbo' einsum with T
    folded into the batch rows.
  - ``attn_core``       — the multi-head attention core (QK^T -> masked
    softmax -> PV) on per-example arithmetic, SHARED VERBATIM by the
    flax transformer modules and the slot twins (models/transformer.py
    imports it), so the fused flat batch and the unrolled per-slot
    reference run bit-identical attention math. Softmax statistics in
    f32 with an explicit in-order add chain for the denominator (the
    GARFIELD_SORTNET-era bitwise discipline: no backend reassociation),
    and a finite large-negative causal mask (never -inf — a masked-row
    ``exp(-inf - -inf)`` NaNs).
  - ``embed`` / ``pos_embed`` — token-embedding gather from the STACKED
    table (the autodiff transpose is a per-slot scatter-add — the
    embedding's per-slot gradient) and the learned-positional broadcast
    add (transpose: per-slot sum over the batch rows).
  - ``gelu``            — re-exported ``jax.nn.gelu`` so model and twin
    share one callable.
  - ``bias_add``        — per-slot bias broadcast onto the flat batch.
  - ``max_pool`` / ``avg_pool`` / ``global_avg_pool`` — plain flat-batch
    ops (no slot resolution needed; kept here so twins import one module).

Every primitive takes a ``SlotCtx``: the per-trace context holding the
slot geometry plus the PRECOMPUTED slot-membership machinery — the
``(slots, slots*nb)`` one-hot matrix and the sorted segment-id vector are
built once per trace and shared by all ~20 BN layers of a deep twin,
instead of re-emitted per layer.

Two env knobs select the per-slot reduction formulations for on-chip A/B
(both read at TRACE time — a change needs a fresh trace, i.e. a new jit or
an unjitted call):

  - ``GARFIELD_SLOTFUSED_BN=matmul|segsum`` (default matmul): per-slot BN
    statistics as the one-hot slot matmul ``S @ (spatial reduce)`` (the r5
    formulation) or as a sorted-segment sum over slot ids
    (``jax.ops.segment_sum`` with ``indices_are_sorted``). The matmul
    keeps everything on the MXU; the segment sum avoids materializing the
    ``(slots, slots*b)`` operand and lowers to an in-order add — which of
    the two schedules better against the backward's grouped dw convs is a
    chip question (PERF.md round 7).
  - ``GARFIELD_SLOTFUSED_DW=grouped|unroll|segsum`` (default grouped):
    the dw formulation of ``slot_conv``'s backward plus its epilogue.
    ``grouped`` and ``unroll`` are the r5 modes (one batch-grouped conv
    vs n per-slot convs + stack); ``segsum`` keeps the grouped dw convs
    but routes the EPILOGUE — the per-slot bias/BN cotangent reductions,
    i.e. the transpose of every ``slot_expand`` broadcast — through the
    same segment machinery (gather forward, sorted segment-sum
    transpose) instead of the ``S.T`` matmul twin, so the ~20 BN
    slot-stat reductions of a deep twin stop competing for the MXU
    against the grouped convs they are scheduled with.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "SlotCtx",
    "slot_conv",
    "conv",
    "bn_train",
    "layer_norm",
    "dense",
    "seq_dense",
    "attn_core",
    "softmax_chain",
    "embed",
    "pos_embed",
    "gelu",
    "bias_add",
    "relu",
    "max_pool",
    "avg_pool",
    "global_avg_pool",
]

_DN = ("NHWC", "HWIO", "NHWC")


def bn_stats_mode():
    """BN per-slot statistics formulation (read at trace time)."""
    return os.environ.get("GARFIELD_SLOTFUSED_BN", "matmul")


def dw_mode():
    """slot_conv dw / epilogue formulation (read at trace time)."""
    return os.environ.get("GARFIELD_SLOTFUSED_DW", "grouped")


class SlotCtx:
    """Per-trace slot geometry + precomputed membership machinery.

    Built once per ``slot_grad_fn`` trace (``slotfused.build_slot_grad_fn``)
    and threaded through every primitive, so the slot matrix / segment ids
    exist once in the traced graph no matter how many layers consume them.
    """

    def __init__(self, slots, nb, dtype):
        self.slots = int(slots)
        self.nb = int(nb)
        self.dtype = dtype
        self.bn_mode = bn_stats_mode()
        self.dw = dw_mode()
        if self.bn_mode not in ("matmul", "segsum"):
            raise ValueError(
                f"GARFIELD_SLOTFUSED_BN must be matmul|segsum, "
                f"got {self.bn_mode!r}"
            )
        if self.dw not in ("grouped", "unroll", "segsum"):
            raise ValueError(
                f"GARFIELD_SLOTFUSED_DW must be grouped|unroll|segsum, "
                f"got {self.dw!r}"
            )
        # Sorted slot-membership ids (example k of the flat batch belongs
        # to slot k // nb) — a host constant; jnp ops lift it once.
        self.seg_ids = np.repeat(np.arange(self.slots), self.nb)
        self._S = {}

    def slot_matrix(self, dtype):
        """Constant (slots, slots*nb) one-hot membership matrix, built at
        most once per dtype per trace.

        Per-slot segment reductions over the flat batch are expressed as
        this tiny matmul instead of a (slots, nb, ...) reshaped reduce:
        XLA lowers the grouped reduce over the MAJOR dim through
        transposing copies (traced 1.4 ms/step at ResNet-18 n=8), while
        ``S @ (per-example reduction)`` stays in natural layouts — and its
        autodiff transpose, ``S.T @ _``, is the equally clean per-slot
        broadcast.
        """
        key = jnp.dtype(dtype).name
        if key not in self._S:
            self._S[key] = jnp.repeat(
                jnp.eye(self.slots, dtype=dtype), self.nb, axis=1
            )
        return self._S[key]


def slot_reduce(ctx, e):
    """Per-slot segment reduction: (slots*nb, C) f32 -> (slots, C) f32.

    ``matmul`` mode: ``S @ e`` (MXU). ``segsum`` mode: sorted segment sum
    over the slot ids (no (slots, slots*nb) operand; in-order adds, so the
    two modes are f32-rounding-equal for equal-length segments summed in
    index order — equality-pinned in tests/test_slotfused.py).
    """
    if ctx.bn_mode == "segsum":
        return jax.ops.segment_sum(
            e, ctx.seg_ids, num_segments=ctx.slots, indices_are_sorted=True
        )
    return ctx.slot_matrix(e.dtype) @ e


def slot_expand(ctx, v_st, spatial_dims):
    """(slots, C) per-slot vector -> flat per-example (slots*nb, 1..1, C).

    ``grouped``/``unroll`` dw modes: the ``S.T`` matmul twin of the stats
    reduction — its autodiff transpose is (spatial reduce -> ``S @ _``),
    the same copy-free route as the forward stats (a broadcast+reshape
    formulation transposes to the 5-D grouped reduce this library avoids).
    ``segsum`` dw mode: a row gather over the sorted slot ids, whose
    transpose is a sorted segment-sum scatter-add — the dw-epilogue
    formulation (module docstring): per-slot bias/BN cotangents leave the
    MXU to the grouped dw convs.
    """
    if ctx.dw == "segsum":
        flat = v_st[ctx.seg_ids]  # gather; transpose = sorted segment sum
    else:
        flat = ctx.slot_matrix(v_st.dtype).T @ v_st  # (slots*nb, C)
    return flat.reshape(
        (flat.shape[0],) + (1,) * spatial_dims + (flat.shape[-1],)
    )


# --------------------------------------------------------------------------
# Convolution: fused primal/dx, per-slot dw (custom vjp)
# --------------------------------------------------------------------------

def _conv(x, w, stride, padding, groups):
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding,
        dimension_numbers=_DN, feature_group_count=groups,
    )


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _slot_conv(x, w_st, stride, padding, slots, groups):
    return _conv(x, w_st[0], stride, padding, groups)


def _slot_conv_fwd(x, w_st, stride, padding, slots, groups):
    return _conv(x, w_st[0], stride, padding, groups), (x, w_st[0])


def _slot_conv_bwd(stride, padding, slots, groups, res, dy):
    """dx fused over the flat batch; dw slot-resolved.

    dw formulations (``GARFIELD_SLOTFUSED_DW``, read at trace time):
    ``grouped`` (default) and ``segsum`` run ONE batch-grouped conv via
    the transpose of the slot-vmapped conv — the (slots, nb) reshape is a
    view of the flat activations, so no per-slot operand copies and the
    (slots, ...) result needs no stacking DUS (``segsum`` differs only in
    the epilogue reductions around the convs — see ``slot_expand``).
    ``unroll`` is the r5 A/B escape hatch: n per-slot convs + stack
    (traced 3.0 ms/step of operand copies + 1.6 ms of stack DUS at n=8
    ResNet-18 — the b=25 slot slices misalign with the (8,128) tile).
    """
    x, w0 = res
    # dx: one fused transposed conv over the whole n*b batch.
    dx = jax.linear_transpose(
        lambda x_: _conv(x_, w0, stride, padding, groups), x
    )(dy)[0]
    nb = x.shape[0] // slots
    xs = x.reshape(slots, nb, *x.shape[1:])
    dys = dy.reshape(slots, nb, *dy.shape[1:])
    if dw_mode() != "unroll":
        def vconv(w_st_):
            return jax.vmap(
                lambda xi, wi: _conv(xi, wi, stride, padding, groups)
            )(xs, w_st_)

        w_like = jnp.broadcast_to(w0[None], (slots,) + w0.shape)
        dw_st = jax.linear_transpose(vconv, w_like)(dys)[0]
        return dx, dw_st
    dws = [
        jax.linear_transpose(
            lambda w_: _conv(xs[i], w_, stride, padding, groups), w0
        )(dys[i])[0]
        for i in range(slots)
    ]
    return dx, jnp.stack(dws)


_slot_conv.defvjp(_slot_conv_fwd, _slot_conv_bwd)


def slot_conv(x, w_st, stride, padding, slots, groups=1):
    """Convolution over the flat (slots*b) batch with a STACKED kernel.

    ``w_st`` is (slots, kh, kw, ci/groups, co) with all slot rows equal (a
    broadcast of the shared kernel); the primal and dx use ``w_st[0]`` at
    the fused batch, and the custom vjp returns the PER-SLOT weight
    gradients as ``w_st``'s cotangent — the only place worker-resolved
    arithmetic is actually required. ``groups`` is
    ``lax.conv_general_dilated``'s ``feature_group_count`` (depthwise
    convs pass ``groups == in_channels``).
    """
    return _slot_conv(x, w_st, stride, padding, slots, groups)


def conv(ctx, x, p_st, stride, padding, groups=1):
    """Layer-level conv: stacked kernel + optional per-slot bias.

    ``p_st`` is the stacked flax param dict (``kernel`` and optionally
    ``bias``); strides/padding accept ints like ``models/_layers.conv``.
    """
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    y = slot_conv(
        x, p_st["kernel"].astype(ctx.dtype), stride, padding, ctx.slots,
        groups,
    )
    if "bias" in p_st:
        y = y + slot_expand(ctx, p_st["bias"].astype(ctx.dtype), x.ndim - 2)
    return y


# --------------------------------------------------------------------------
# BatchNorm (train mode), per-slot statistics
# --------------------------------------------------------------------------

def bn_train(ctx, x, p_st, stats, momentum=0.9, eps=1e-5):
    """Per-slot BatchNorm (train mode), flax-numerics-compatible.

    Statistics are computed in f32 over each slot's (b, H, W) block (flax
    nn.BatchNorm computes f32 stats with the fast mean-of-squares
    variance) via ``slot_reduce`` — the one-hot slot matmul or the sorted
    segment sum, per ``GARFIELD_SLOTFUSED_BN``; the normalize runs on the
    FLAT batch in the compute dtype with the per-slot stats expanded back.
    Returns ``(y, {"mean": (slots, C), "var": (slots, C)})`` where the new
    running stats follow flax's ``m*old + (1-m)*batch`` per slot — the
    per-worker semantics the unroll path produces.
    """
    # Stats width follows flax _compute_stats: at least f32, wider if the
    # activations are wider (f64 under an x64 pipeline — what the tight
    # structural equality pins in tests/test_slotfused.py run under).
    xf = x.astype(jnp.promote_types(jnp.float32, x.dtype))
    spatial = tuple(range(1, xf.ndim - 1))
    denom = 1.0 / (ctx.nb * int(np.prod([x.shape[a] for a in spatial])))
    e1 = jnp.sum(xf, axis=spatial)          # (slots*nb, C)
    e2 = jnp.sum(xf * xf, axis=spatial)     # (slots*nb, C)
    mean = slot_reduce(ctx, e1) * denom     # (slots, C)
    var = slot_reduce(ctx, e2) * denom - mean * mean
    new_stats = {
        "mean": momentum * stats["mean"][None] + (1.0 - momentum) * mean,
        "var": momentum * stats["var"][None] + (1.0 - momentum) * var,
    }
    new_stats = jax.tree.map(jax.lax.stop_gradient, new_stats)
    sd = x.ndim - 2
    # Exactly flax _normalize's association — y = (x - mean) * (rsqrt(var
    # + eps) * scale) + bias — so the twin's float rounding tracks the flax
    # path as closely as the fused batch allows (a reassociated scale/shift
    # form measured ~1e-3 relative after 20 layers of amplification).
    # Stats stay f32 (flax _compute_stats); the elementwise normalize runs
    # in the COMPUTE dtype like flax _normalize — an f32 normalize would
    # double the HBM traffic of every BN under the bf16 pipeline.
    dtype = ctx.dtype
    mul = (jax.lax.rsqrt(var + eps)
           * p_st["scale"].astype(xf.dtype)).astype(dtype)
    y = (
        (x.astype(dtype) - slot_expand(ctx, mean.astype(dtype), sd))
        * slot_expand(ctx, mul, sd)
        + slot_expand(ctx, p_st["bias"].astype(dtype), sd)
    )
    return y, new_stats


# --------------------------------------------------------------------------
# Dense / bias / activations / pooling (flat-batch ops)
# --------------------------------------------------------------------------

def dense(ctx, x2, p_st):
    """(slots*b, F) @ per-slot kernel -> (slots, b, O) via a slot-batched
    matmul; autodiff's dk is a slot-batched matmul too (MXU-native)."""
    x3 = x2.reshape(ctx.slots, ctx.nb, -1).astype(ctx.dtype)
    y = jnp.einsum("sbf,sfo->sbo", x3, p_st["kernel"].astype(ctx.dtype))
    if "bias" in p_st:
        y = y + p_st["bias"].astype(ctx.dtype)[:, None, :]
    return y


def seq_dense(ctx, x, p_st):
    """Sequence-layout dense: (slots*b, T, F) @ per-slot kernel.

    The T axis folds into the per-slot batch rows, so this is the same
    MXU-native 'sbf,sfo->sbo' contraction as ``dense`` — flax
    ``nn.Dense`` on (b, T, F) contracts the last dim identically, so
    the twin-vs-unroll difference is only the slot batching of the
    kernel operand. Returns (slots*b, T, O).
    """
    T = x.shape[1]
    x3 = x.reshape(ctx.slots, ctx.nb * T, -1).astype(ctx.dtype)
    y = jnp.einsum("sbf,sfo->sbo", x3, p_st["kernel"].astype(ctx.dtype))
    if "bias" in p_st:
        y = y + p_st["bias"].astype(ctx.dtype)[:, None, :]
    return y.reshape(ctx.slots * ctx.nb, T, -1)


# --------------------------------------------------------------------------
# Transformer primitives: LayerNorm / attention / embeddings
# --------------------------------------------------------------------------

#: Finite large-negative causal-mask value. NOT -inf: a masked score of
#: -inf makes ``exp(s - max)`` evaluate ``exp(-inf - -inf)`` = NaN the
#: moment a row is fully masked, and the softmax add chain propagates it.
#: exp(-1e30 - m) underflows to exact 0.0 in f32 and f64, so masked
#: positions contribute nothing to the denominator deterministically.
MASK_VALUE = -1e30

#: One shared GELU for models and twins (tanh approximation, the
#: ``jax.nn.gelu`` default) — sharing the callable is what keeps the
#: fused and unrolled pipelines on identical elementwise arithmetic.
gelu = jax.nn.gelu


def softmax_chain(s):
    """Softmax over the last axis with an EXPLICIT in-order add chain.

    Max-subtracted for range safety (statistics stay in the operand's
    dtype — callers promote to at least f32 first, the attention-numerics
    rule), with the denominator built as ``e_0 + e_1 + ... + e_{T-1}`` in
    index order instead of a ``jnp.sum`` the backend may reassociate —
    the same in-order-adds discipline ``slot_reduce``'s segsum mode pins,
    so fused-vs-unrolled softmax rows agree bitwise for any schedule.
    No zero-denominator guard is needed: the max subtraction guarantees
    one exact ``exp(0) = 1`` term per row.
    """
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    e = jnp.exp(s - m)
    acc = e[..., 0]
    for t in range(1, e.shape[-1]):
        acc = acc + e[..., t]
    return e / acc[..., None]


def attn_core(q, k, v, causal=False):
    """Multi-head attention core on (..., T, H, Dh) q/k/v.

    Per-EXAMPLE arithmetic only — no slot resolution anywhere — so the
    flax transformer modules (models/transformer.py) call this exact
    function on (b, T, H, Dh) while the twins call it on the flat
    (slots*b, T, H, Dh): fused and unrolled attention are the same
    traced ops, and the twin equality pins only have to absorb the
    per-slot QKV/out projections around it.

    Numerics per the attention playbook: QK^T accumulates in (at least)
    f32 via ``preferred_element_type``, softmax statistics stay in that
    width (``softmax_chain``: max-subtract + in-order add chain), the
    causal mask is a finite ``MASK_VALUE`` where-select over an iota
    row/col comparison, and the probabilities are cast back to the
    compute dtype only for the PV contraction.
    """
    dh = q.shape[-1]
    sf = jnp.promote_types(jnp.float32, q.dtype)
    s = jnp.einsum(
        "...qhd,...khd->...hqk", q, k, preferred_element_type=sf
    ) * (1.0 / float(np.sqrt(dh)))
    if causal:
        T = s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        col = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where(col <= row, s, jnp.asarray(MASK_VALUE, s.dtype))
    p = softmax_chain(s)
    return jnp.einsum("...hqk,...khd->...qhd", p.astype(q.dtype), v)


def layer_norm(ctx, x, p_st, eps=1e-6):
    """Per-slot-affine LayerNorm over the flat batch, flax numerics.

    The statistics are PER-EXAMPLE (feature-axis mean/fast-variance in
    at least f32, negative variances clipped — flax ``_compute_stats``),
    so unlike ``bn_train`` they need no slot resolution at all; only the
    scale/bias application is worker-resolved, via ``slot_expand``
    (whose autodiff transpose is the per-slot segment reduction — the
    per-slot LayerNorm parameter gradients). Association matches flax
    ``_normalize`` exactly: ``y = (x - mean) * (rsqrt(var + eps) *
    scale) + bias``, cast to the compute dtype at the end.
    """
    xf = x.astype(jnp.promote_types(jnp.float32, x.dtype))
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(
        0.0, jnp.mean(xf * xf, axis=-1, keepdims=True) - mu * mu
    )
    sd = x.ndim - 2
    mul = lax.rsqrt(var + eps) * slot_expand(
        ctx, p_st["scale"].astype(xf.dtype), sd
    )
    y = (xf - mu) * mul + slot_expand(
        ctx, p_st["bias"].astype(xf.dtype), sd
    )
    return y.astype(ctx.dtype)


def embed(ctx, tok, emb_st):
    """Token-embedding lookup from the STACKED (slots, vocab, D) table.

    Forward gathers each slot's rows from its own table copy (all rows
    equal by construction, so the values match the fused single-table
    lookup flax ``nn.Embed`` performs); the autodiff transpose of the
    slot-vmapped gather is a per-slot scatter-add — exactly the
    per-worker embedding gradient, with no custom vjp needed.
    """
    tok3 = tok.reshape((ctx.slots, ctx.nb) + tok.shape[1:])
    out = jax.vmap(lambda tab, t: jnp.take(tab, t, axis=0))(
        emb_st.astype(ctx.dtype), tok3
    )
    return out.reshape((ctx.slots * ctx.nb,) + out.shape[2:])


def pos_embed(ctx, x, pos_st):
    """Add learned per-slot positional embeddings (slots, T, D) onto the
    flat (slots*b, T, D) activations. The (slots, nb) view is free; the
    broadcast-add's transpose is a per-slot sum over the nb rows — the
    positional table's per-worker gradient."""
    xs = x.reshape((ctx.slots, ctx.nb) + x.shape[1:])
    y = xs + pos_st[:, None].astype(ctx.dtype)
    return y.reshape(x.shape)


def bias_add(ctx, x, b_st):
    """Add a (slots, C) per-slot bias onto the flat (slots*b, ..., C)."""
    return x + slot_expand(ctx, b_st.astype(ctx.dtype), x.ndim - 2)


def relu(x):
    return jax.nn.relu(x)


def max_pool(x, window=2, stride=None, padding=0):
    """NHWC max pool over the flat batch (int padding like _layers)."""
    stride = window if stride is None else stride
    pad = (
        ((0, 0), (padding, padding), (padding, padding), (0, 0))
        if isinstance(padding, int) else padding
    )
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        (1, window, window, 1), (1, stride, stride, 1), pad,
    )


def avg_pool(x, window=2, stride=None):
    """NHWC average pool (VALID), matching ``_layers.avg_pool``."""
    stride = window if stride is None else stride
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        (1, window, window, 1), (1, stride, stride, 1), "VALID",
    )
    return summed / (window * window)


def global_avg_pool(x):
    """NHWC global average pool -> (N, C)."""
    return jnp.mean(x, axis=(1, 2))

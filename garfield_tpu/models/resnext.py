"""ResNeXt (counterpart of garfieldpp/models/resnext.py): grouped 3x3
bottlenecks, CIFAR 29-layer variants."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class ResNeXtBlock(nn.Module):
    cardinality: int
    bottleneck_width: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        group_width = self.cardinality * self.bottleneck_width
        out = nn.relu(norm(train, dtype=d)(conv1x1(group_width, dtype=d)(x)))
        out = nn.relu(norm(train, dtype=d)(
            conv(group_width, 3, self.stride, padding=1,
                 groups=self.cardinality, dtype=d)(out)))
        out = norm(train, dtype=d)(conv1x1(2 * group_width, dtype=d)(out))
        if self.stride != 1 or x.shape[-1] != 2 * group_width:
            x = norm(train, dtype=d)(
                conv1x1(2 * group_width, stride=self.stride, dtype=d)(x))
        return nn.relu(out + x)


class ResNeXt(nn.Module):
    num_blocks: tuple
    cardinality: int
    bottleneck_width: int
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(64, 1, 1, padding=0, dtype=d)(x)))
        width = self.bottleneck_width
        for stage, nb in enumerate(self.num_blocks):
            for i in range(nb):
                stride = 2 if stage > 0 and i == 0 else 1
                x = ResNeXtBlock(self.cardinality, width, stride, dtype=d)(x, train)
            width *= 2
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def ResNeXt29_2x64d(num_classes=10, dtype=jnp.float32):
    return ResNeXt((3, 3, 3), 2, 64, num_classes, dtype)


def ResNeXt29_4x64d(num_classes=10, dtype=jnp.float32):
    return ResNeXt((3, 3, 3), 4, 64, num_classes, dtype)


def ResNeXt29_8x64d(num_classes=10, dtype=jnp.float32):
    return ResNeXt((3, 3, 3), 8, 64, num_classes, dtype)


def ResNeXt29_32x4d(num_classes=10, dtype=jnp.float32):
    return ResNeXt((3, 3, 3), 32, 4, num_classes, dtype)

"""Bulyan (over Multi-Krum) GAR.

Counterpart of pytorch_impl/libs/aggregators/bulyan.py (:31-84): requires
n >= 4f+3 (:114). Two phases:

1. Selection: n-2f-2 rounds. In round i, each still-active node is scored by
   the sum of its m_i smallest distances to the other active nodes, with
   m_i = min(m, (n-f-2) - i) and m defaulting to n-f-2 (bulyan.py:49-56);
   the round emits the Multi-Krum average of the m_i best-scored active
   gradients (bulyan.py:68) and prunes the single best-scored node.
2. Coordinate-wise averaged median over the n-2f-2 emitted vectors: per
   coordinate, average the beta = (n-2f-2) - 2f values closest to the
   (lower) median (bulyan.py:77-84).

NOTE: the reference's incremental score update after pruning is buggy (it
reads an undefined ``distance[gid]`` and misindexes ``scores[gid]``,
bulyan.py:74-76 — only reached on score ties). This implementation
recomputes scores from the active set each round, which is the intended
semantics and side-steps the bug; equivalence with the reference holds
whenever the reference path is well-defined.

TPU design: one Gram-matmul distance matrix reused across rounds; the
sequential selection is a ``lax.fori_loop`` whose body is masked sort +
prefix-sum + dynamic index — no host sync, compiles to a single XLA while
loop (the reference needed its largest CUDA kernel here, py_bulyan/bulyan.cu).
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import as_stack, num_gradients, pairwise_distances


def aggregate(gradients, f, m=None, **kwargs):
    """Bulyan over Multi-Krum."""
    g = as_stack(gradients)
    n, d = g.shape
    m_max = n - f - 2
    if m is None:
        m = m_max
    rounds = n - 2 * f - 2
    dist = pairwise_distances(g)  # (n, n), diag/non-finite -> +inf

    # The selection loop only needs the (n, n) distance matrix: each round
    # scores the active nodes, records the Multi-Krum selection *weights*
    # (1/m_i on the m_i best, 0 elsewhere), and prunes the best node. The
    # selected averages are then ONE (rounds, n) @ (n, d) matmul after the
    # loop — the loop never touches the d-sized stack, so the whole phase
    # costs a single MXU pass over g instead of rounds x (gather + cumsum).
    def round_body(i, carry):
        active, weights = carry
        m_i = jnp.minimum(m, m_max - i)
        pair_ok = active[:, None] & active[None, :]
        masked = jnp.where(pair_ok, dist, jnp.inf)
        csum = jnp.cumsum(jnp.sort(masked, axis=1), axis=1)
        scores = jax.lax.dynamic_index_in_dim(csum, m_i - 1, axis=1, keepdims=False)
        scores = jnp.where(active, scores, jnp.inf)
        order = jnp.argsort(scores)  # stable: ties break on lowest index
        w = jnp.zeros((n,), g.dtype).at[order].set(
            (jnp.arange(n) < m_i).astype(g.dtype) / m_i
        )
        weights = weights.at[i].set(w)
        active = active.at[order[0]].set(False)
        return active, weights

    active0 = jnp.ones((n,), dtype=bool)
    weights0 = jnp.zeros((rounds, n), dtype=g.dtype)
    _, weights = jax.lax.fori_loop(0, rounds, round_body, (active0, weights0))
    # Rows never selected in any round must not poison the matmul with
    # NaN/Inf coordinates (0 * inf = nan); rows that are selected pass
    # through untouched (reference mean semantics).
    used = jnp.any(weights != 0, axis=0)
    selected = weights @ jnp.where(used[:, None], g, 0)  # (rounds, d)

    # Coordinate-wise averaged median (bulyan.py:77-84); fused Pallas kernel
    # on TPU (garfield_tpu/ops/coordinate.py); off the Pallas path the
    # gather-free threshold formulation (averaged_median_mean_xla), so
    # n > MAX_SORT_N degrades gracefully instead of hitting the
    # catastrophic sort+argsort+gather.
    from .. import ops

    beta = rounds - 2 * f
    return ops.averaged_median_mean(selected, beta)


def check(gradients, f, m=None, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 4 * f + 3:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 3) // 4}"
        )
    if m is not None and (not isinstance(m, int) or m < 1 or m > n - f - 2):
        return (
            f"invalid number of selected gradients, got m = {m!r}, "
            f"expected 1 <= m <= {n - f - 2}"
        )
    return None


def upper_bound(n, f, d):
    """Same bound as (Multi-)Krum (bulyan.py:117-126)."""
    return 1 / math.sqrt(
        2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2))
    )


register("bulyan", aggregate, check, upper_bound=upper_bound)

"""Telemetry exporters: schema-versioned JSONL, Prometheus text, validation.

One record schema serves every producer (training loops, the cluster
driver, ``bench.py``, ``gar_bench.py``) so consumers — the driver's
BENCH_r* capture, dashboards, the tier-1 schema check — parse one format:

    {"schema": "garfield-telemetry", "v": 1, "kind": <kind>, ...}

Kinds: ``run`` (header: config/meta), ``step`` (per-step tap + loss +
timing), ``event`` (liveness / exchange waits / wire accounting),
``summary`` (run-closing suspicion + counters + wire totals), ``bench``
(bench.py's north-star line), ``gar_bench`` (per-cell kernel latencies),
``transfer_bench`` (mesh all-gather cells), and ``exchange_bench``
(host-plane publish/collect cells — the wire-codec A/B record).
``validate_record`` / ``validate_jsonl`` are stdlib-only and run in the
tier-1 suite, so a malformed artifact fails loudly instead of going dark
(the BENCH_r05 rc=1 post-mortem this subsystem exists for).
"""

import json
import numbers

__all__ = [
    "SCHEMA",
    "SCHEMA_VERSION",
    "JsonlExporter",
    "make_record",
    "prometheus_text",
    "append_record",
    "validate_record",
    "validate_jsonl",
]

SCHEMA = "garfield-telemetry"
# v2 (round 9): summary.step_time gained p50_s/p95_s/p99_s tail
# percentiles (the chunked-dispatch win lives in the tail, not the mean)
# and bench records gained the chunk_steps attribution field. v3 (round
# 10): the ``hier_bench`` kind (hierarchical bucketed-GAR sweep cells —
# HIERBENCH_r*'s format, with peak-RSS accounting), ``gar_bench`` rows may
# carry ``peak_rss_bytes``, and bench error records may carry
# ``backend_outage`` (the BENCH_r05/MULTICHIP_r05 filter). v4 (round 11,
# the bounded-staleness async plane — DESIGN.md §14): the per-round
# ``staleness`` EVENT (per-rank staleness + discount weights, validated
# below), ``summary.staleness`` digest (count/mean/max/hist), and
# ``exchange_bench`` rows may carry ``peak_rss_bytes`` plus the
# straggler-scenario fields (``straggler_ms``, ``sync_round_s``,
# ``async_round_s``, ``speedup``). v5 (round 12, distributed round
# tracing — telemetry/trace.py): the ``span`` kind (one timed phase of
# a round: ``phase``, wall-clock start ``t_wall``, monotonic ``dur_s``,
# optional ``step``/``who``/``tid`` tags — the raw material of
# ``telemetry.report``'s causal timeline), ``summary`` gained the
# optional ``spans`` count + per-phase ``phases`` digest, and
# ``exchange_bench`` rows may carry per-phase ``phases`` percentiles
# plus the tracing A/B fields (``trace_off_round_s``,
# ``trace_on_round_s``, ``trace_overhead``). v6 (round 13, elastic
# asynchrony — DESIGN.md §15): exchange events are PLANE-TAGGED
# (``exchange_wait``/``staleness`` may carry ``plane``; per-step
# ``wire`` events may carry a per-plane byte breakdown under
# ``planes``), the new ``autoscale`` EVENT (action/rank/active/rate/
# target — validated below) with its ``summary.autoscale`` digest
# (spawns/retires/active_workers) and the ``garfield_active_workers``
# Prometheus gauge, and ``exchange_bench`` rows may carry the
# scaleup/scaledown scenario fields (``pre_rate``, ``spike_rate``,
# ``recovered_rate``, ``active_initial``, ``active_final``,
# ``spawns``, ``retires``) plus the LEARN-scenario fields
# (``learn_ms0_bitwise``). v7 (round 14, adaptive adversaries and the
# closed-loop defense — DESIGN.md §16): the ``attack_adapt`` EVENT (one
# adaptive-controller observation: magnitude played, detected verdict,
# bracket), the ``defense_weights`` EVENT (the PS's per-round
# suspicion-weight vector), the ``defense_escalate`` EVENT (one rule-
# ladder transition), the ``attack_fallback`` EVENT (a randomized/
# rotated attack keeping the where-path, emitted once — benches stop
# misattributing fold-path wins), ``summary`` gained
# ``suspicion_decayed``/``suspicion_halflife`` (the windowed score a
# rotated cohort cannot launder) plus the ``defense``/``attack_adapt``
# digests, and the new ``defense_bench`` kind (DEFBENCH_r*'s
# accuracy-cell rows). Older records still validate — consumers key on
# field presence, not version. v8 (round 15, the full threat-model
# matrix — DESIGN.md §17): the ``ps_attack_adapt`` EVENT (one MODEL-
# plane adaptive-controller observation — a Byzantine PS bisecting
# against the replica gather, or a LEARN node against the gossip; same
# fields as ``attack_adapt`` plus an optional ``plane`` tag), the
# ``targeted_eval`` EVENT (the per-class eval digest: per-class
# accuracy, source→target confusion, backdoor attack-success-rate —
# what makes a suspicion-blind targeted attack measurable), ``summary``
# gained the optional ``targeted`` digest (events/last_confusion/
# last_asr), ``defense_weights`` events and ``defense_escalate`` events
# may carry a ``plane`` tag (gradient/model/gossip — the per-plane
# ladder deployment), and ``defense_bench`` rows may carry ``plane``/
# ``confusion``/``asr``/``clean_confusion`` (the plane column and the
# targeted rows' success metric). v9 (round 16, the data-plane defense —
# DESIGN.md §18): the ``data_defense`` EVENT (one round of the
# fingerprint detectors: per-rank spectral outlier ``scores``, the
# tau-sigma/2-means ``flags``, the composed ``weights``, optional
# ``ranks``/``plane`` attribution — validated below), ``summary`` gained
# the optional ``data_defense`` digest (rounds/flagged/max_score/min_w)
# and the ``garfield_dataplane_outlier_score`` Prometheus gauge,
# ``targeted_eval`` events and ``defense_bench`` rows may carry
# ``asr_baseline`` (the clean-model trigger-rate floor — ASR cells
# report attributable lift, not raw rate), and ``defense_bench``
# ``defense`` strings may name the composed modes (``data``/
# ``escalate+data``).
# v10 (round 17, the federated round engine — DESIGN.md §19): the
# ``fed_round`` EVENT (one sharded federated round: shard count, active
# cohort size, the cohort's priced ``f_budget``, the simulation-side
# ``realized_byz``/``budget_exceeded`` audit, round wall and a
# ``per_shard`` digest of per-shard fold latencies and wire bytes), the
# ``cohort`` EVENT (the audited cohort's stable GLOBAL ``client_ids``
# with their composed ``selected`` weights — what the hub's
# client-id-keyed decayed suspicion folds, the score resampling cannot
# launder), ``summary`` gained the optional ``federated`` digest
# (rounds/shards/last_cohort/f_budget/budget_exceeded/mean_round_s +
# ``top_clients``), the ``garfield_fed_*`` /
# ``garfield_client_suspicion_decayed`` Prometheus series, and the new
# ``fed_bench`` kind (FEDBENCH_r*'s rows: the 1/S shard-scaling cells,
# the S=1 bitwise anchor, the autoscaled fleet-rate cells).
# v11 (round 18, the compressed wire — DESIGN.md §20): the ``wire``
# EVENT gained the per-SCHEME byte breakdown (``schemes`` sub-object:
# f32/bf16/int8/int4/topk, each {bytes_out, bytes_in}) plus the
# optional ``compression_ratio`` (send-side f32-equivalent bytes /
# actual bytes this step) and ``ef_residual_norm`` (the gradient-plane
# error-feedback accumulator's L2 norm) fields — all validated below —
# ``summary`` gained the optional ``wire_schemes`` digest, the
# ``garfield_wire_bytes_total{scheme=}`` Prometheus counters landed
# beside the direction-only totals, and ``exchange_bench`` rows may
# carry the EXCHBENCH_r05 robustness-cell fields (``cell``,
# ``final_accuracy``, ``attack_magnitude``, ``headroom``,
# ``compression_ratio``, ``matched_accuracy``).
# v12 (round 19, kernel-grade robust selection — DESIGN.md §21):
# ``fed_bench`` rows may carry a ``phases`` sub-object (the
# exchange_bench v5 shape: phase name -> numeric stat object, here the
# per-phase ingest/h2d/fold/selection p50/p95 from the trace plane — a
# scaling row attributes WHERE its round time went, not just how much),
# and ``gar_bench`` rows may carry the --selection micro-mode fields
# (``grid``, ``impl`` — sortnet vs xla_sort as explicit closures —
# ``wave_buckets``, ``per_bucket_s``), all validated below.
# v13 (round 20, the control plane — DESIGN.md §22): the ``membership``
# EVENT (one membership change: the new ``epoch`` — or null on a
# pre-epoch deployment — the ``action`` that caused it
# (failover/split/merge), the affected ``shard`` when there is one, the
# resulting ``num_shards``, and the round as ``step``), and the new
# ``soak_bench`` kind (SOAKBENCH_r*'s rows: one sustained-load scenario
# each — steady / rolling_restart / partition / churn — with round
# counts, p50/p95/p99 round latency from the trace plane, the
# failover/partition/epoch accounting, and the measured
# ``kill_cost_rounds`` for the mid-round-kill SLO).
# v14 (round 21, slot-fused transformers — DESIGN.md §23): the new
# ``trans_bench`` kind (TRANSBENCH_r*'s rows). Two row families share
# it: A/B rows (one ``model`` x ``path`` cell — path ``fused`` is the
# slot-fused twin, ``unrolled`` the per-slot reference loop — with
# ``per_slot_grad_s``, ``speedup`` on the fused row, and the gar_bench
# rep/trial/dce-guard columns) and robustness rows (``cell`` names the
# scenario — e.g. ``backdoor/none`` vs ``backdoor/data`` — with
# ``asr``, ``asr_baseline`` (the v9 attribution discipline: report
# attributable lift, not raw rate), ``accuracy`` and ``defense``).
# ``gar_bench`` --selection rows additionally sweep the
# attention-shaped d regimes (heads * d_head * seq) — no new fields.
# v15 (round 22, batched wire ingest — DESIGN.md §24): the new
# ``ingest_batch`` EVENT (one bulk ``push_frames`` call on a shard
# server: the ``shard``, how many ``frames`` arrived, how many were
# ``rejected`` with ban attribution, the accepted ``bytes``, whether
# the vectorized ``batched`` decode path ran or the call fell back to
# per-frame decode, the wall ``dur_s``, and the round as ``step``),
# the ``garfield_ingest_batch_seconds`` Prometheus series beside the
# wire codec counters, and the ``fed_bench`` check="ingest_micro" row
# family (INGESTBENCH_r*: batch-vs-per-frame decode isolation — extra
# numeric columns like ``per_frame_s``/``batch_s``/``batch`` and a
# ``scheme`` string ride the kind's open extra-field policy; the
# required check/n/d/shards/gar envelope still applies).
SCHEMA_VERSION = 15

KINDS = ("run", "step", "event", "summary", "bench", "gar_bench",
         "transfer_bench", "exchange_bench", "hier_bench", "span",
         "defense_bench", "fed_bench", "soak_bench", "trans_bench")


def make_record(kind, **fields):
    """Stamp ``fields`` with the schema envelope."""
    if kind not in KINDS:
        raise ValueError(f"unknown telemetry record kind {kind!r}")
    return {"schema": SCHEMA, "v": SCHEMA_VERSION, "kind": kind, **fields}


class JsonlExporter:
    """Line-buffered JSONL writer (one record per line, flushed — a
    crashed run keeps every record written before the crash)."""

    def __init__(self, path, append=False):
        self.path = str(path)
        self._fp = open(self.path, "a" if append else "w")

    def write(self, record):
        validate_record(record)
        self._fp.write(json.dumps(record) + "\n")
        self._fp.flush()
        return record

    def close(self):
        if self._fp is not None:
            self._fp.close()
            self._fp = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def append_record(path, record):
    """One-shot append (bench entry points: no long-lived exporter)."""
    validate_record(record)
    with open(path, "a") as fp:
        fp.write(json.dumps(record) + "\n")
    return record


# --- validation (stdlib only) ----------------------------------------------


def _fail(msg):
    raise ValueError(f"telemetry schema violation: {msg}")


def _is_num(v):
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def _check_float_list(rec_kind, name, val, length=None):
    if not isinstance(val, list) or not all(_is_num(x) for x in val):
        _fail(f"{rec_kind}.{name} must be a list of numbers, got {val!r}")
    if length is not None and len(val) != length:
        _fail(
            f"{rec_kind}.{name} has {len(val)} entries, expected {length}"
        )


def validate_record(rec):
    """Raise ValueError unless ``rec`` is a well-formed telemetry record."""
    if not isinstance(rec, dict):
        _fail(f"record must be an object, got {type(rec).__name__}")
    if rec.get("schema") != SCHEMA:
        _fail(f"schema must be {SCHEMA!r}, got {rec.get('schema')!r}")
    v = rec.get("v")
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        _fail(f"v must be a positive int, got {v!r}")
    kind = rec.get("kind")
    if kind not in KINDS:
        _fail(f"kind must be one of {KINDS}, got {kind!r}")
    if kind == "step":
        step = rec.get("step")
        if not isinstance(step, int) or isinstance(step, bool) or step < 0:
            _fail(f"step.step must be a non-negative int, got {step!r}")
        for key in ("loss", "step_time_s"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(f"step.{key} must be a number or null, got {val!r}")
        tap = rec.get("tap")
        if tap is not None:
            if not isinstance(tap, dict):
                _fail(f"step.tap must be an object, got {tap!r}")
            obs = tap.get("observed")
            _check_float_list("tap", "observed", obs)
            for key in ("selected", "score"):
                _check_float_list("tap", key, tap.get(key), len(obs))
            for key in ("tau", "clip_frac"):
                if not _is_num(tap.get(key)):
                    _fail(f"tap.{key} must be a number, got {tap.get(key)!r}")
    elif kind == "event":
        if not isinstance(rec.get("event"), str):
            _fail(f"event.event must be a string, got {rec.get('event')!r}")
        if rec.get("event") == "staleness":
            # v4: the async quorum audit — parallel per-rank lists.
            ranks = rec.get("ranks")
            _check_float_list("staleness", "ranks", ranks)
            for key in ("staleness", "weights"):
                _check_float_list(
                    "staleness", key, rec.get(key), len(ranks)
                )
            step = rec.get("step")
            if not isinstance(step, int) or isinstance(step, bool) or step < 0:
                _fail(
                    f"staleness.step must be a non-negative int, "
                    f"got {step!r}"
                )
        elif rec.get("event") in ("attack_adapt", "ps_attack_adapt"):
            # v7: one adaptive-controller observation (DESIGN.md §16);
            # v8 adds the MODEL-plane twin ``ps_attack_adapt`` (a
            # Byzantine PS vs the replica gather / a LEARN node vs the
            # gossip) with an optional plane tag.
            ev = rec["event"]
            if not _is_num(rec.get("magnitude")):
                _fail(
                    f"{ev}.magnitude must be a number, got "
                    f"{rec.get('magnitude')!r}"
                )
            for key in ("lo", "hi"):
                val = rec.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"{ev}.{key} must be a number or null, "
                        f"got {val!r}"
                    )
            det = rec.get("detected")
            if det is not None and not isinstance(det, bool) \
                    and not _is_num(det):
                _fail(
                    f"{ev}.detected must be a bool/number or "
                    f"null, got {det!r}"
                )
            plane = rec.get("plane")
            if plane is not None and not isinstance(plane, str):
                _fail(f"{ev}.plane must be a string or null, got {plane!r}")
        elif rec.get("event") == "targeted_eval":
            # v8: the per-class eval digest of a targeted-attack run —
            # what the suspicion plane cannot see, made measurable.
            for key in ("source", "target"):
                val = rec.get(key)
                if not isinstance(val, int) or isinstance(val, bool):
                    _fail(
                        f"targeted_eval.{key} must be an int, got {val!r}"
                    )
            for key in ("confusion", "asr", "accuracy",
                        # v9: the clean-model trigger-rate floor.
                        "asr_baseline"):
                val = rec.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"targeted_eval.{key} must be a number or null, "
                        f"got {val!r}"
                    )
            pc = rec.get("per_class")
            if pc is not None:
                if not isinstance(pc, dict) or not all(
                    _is_num(v) for v in pc.values()
                ):
                    _fail(
                        f"targeted_eval.per_class must map classes to "
                        f"numbers, got {pc!r}"
                    )
        elif rec.get("event") == "defense_weights":
            # v7: the PS's per-round suspicion-weight vector.
            ws = rec.get("weights")
            _check_float_list("defense_weights", "weights", ws)
            ranks = rec.get("ranks")
            if ranks is not None:
                _check_float_list(
                    "defense_weights", "ranks", ranks, len(ws)
                )
        elif rec.get("event") == "data_defense":
            # v9: one round of the data-plane detectors (aggregators/
            # dataplane.py): per-rank spectral outlier scores, the
            # tau-sigma/2-means flags, the weights composed into the
            # quorum, optional rank attribution + plane tag.
            sc = rec.get("scores")
            _check_float_list("data_defense", "scores", sc)
            for key in ("flags", "weights", "ranks"):
                val = rec.get(key)
                if val is not None:
                    _check_float_list("data_defense", key, val, len(sc))
            plane = rec.get("plane")
            if plane is not None and not isinstance(plane, str):
                _fail(
                    f"data_defense.plane must be a string or null, "
                    f"got {plane!r}"
                )
            step = rec.get("step")
            if step is not None and (
                not isinstance(step, int) or isinstance(step, bool)
                or step < 0
            ):
                _fail(
                    f"data_defense.step must be a non-negative int or "
                    f"null, got {step!r}"
                )
        elif rec.get("event") == "defense_escalate":
            # v7: one rule-ladder transition of the closed-loop defense.
            lvl = rec.get("level")
            if not isinstance(lvl, int) or isinstance(lvl, bool) or lvl < 0:
                _fail(
                    f"defense_escalate.level must be a non-negative int, "
                    f"got {lvl!r}"
                )
            if not isinstance(rec.get("rule"), str):
                _fail(
                    f"defense_escalate.rule must be a string, got "
                    f"{rec.get('rule')!r}"
                )
            if rec.get("direction") not in ("escalate", "deescalate"):
                _fail(
                    f"defense_escalate.direction must be 'escalate' or "
                    f"'deescalate', got {rec.get('direction')!r}"
                )
        elif rec.get("event") == "attack_fallback":
            # v7: a fold-ineligible attack keeping the where-path, made
            # loud (one-time per process).
            for key in ("attack", "path", "why"):
                if not isinstance(rec.get(key), str):
                    _fail(
                        f"attack_fallback.{key} must be a string, got "
                        f"{rec.get(key)!r}"
                    )
        elif rec.get("event") == "fed_round":
            # v10: one sharded federated round (federated/engine.py).
            for key in ("shards", "cohort"):
                val = rec.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 1:
                    _fail(
                        f"fed_round.{key} must be a positive int, "
                        f"got {val!r}"
                    )
            for key in ("step", "f_budget", "realized_byz"):
                val = rec.get(key)
                if val is not None and (
                    not isinstance(val, int) or isinstance(val, bool)
                    or val < 0
                ):
                    _fail(
                        f"fed_round.{key} must be a non-negative int or "
                        f"null, got {val!r}"
                    )
            be = rec.get("budget_exceeded")
            if be is not None and not isinstance(be, bool):
                _fail(
                    f"fed_round.budget_exceeded must be a bool or null, "
                    f"got {be!r}"
                )
            rs = rec.get("round_s")
            if rs is not None and not _is_num(rs):
                _fail(
                    f"fed_round.round_s must be a number or null, "
                    f"got {rs!r}"
                )
            ps = rec.get("per_shard")
            if ps is not None:
                if not isinstance(ps, dict) or not all(
                    isinstance(v, dict) and all(
                        x is None or _is_num(x) for x in v.values()
                    )
                    for v in ps.values()
                ):
                    _fail(
                        f"fed_round.per_shard must map shard ids to "
                        f"numeric digests, got {ps!r}"
                    )
        elif rec.get("event") == "cohort":
            # v10: the audited cohort — stable global client ids with
            # their composed selected weights (parallel lists).
            ids = rec.get("client_ids")
            _check_float_list("cohort", "client_ids", ids)
            sel = rec.get("selected")
            if sel is not None:
                _check_float_list("cohort", "selected", sel, len(ids))
            fb = rec.get("f_budget")
            if fb is not None and (
                not isinstance(fb, int) or isinstance(fb, bool) or fb < 0
            ):
                _fail(
                    f"cohort.f_budget must be a non-negative int or "
                    f"null, got {fb!r}"
                )
        elif rec.get("event") == "wire":
            # v11: the per-step wire digest (apps/cluster.WireStats) —
            # byte totals, the per-plane/per-scheme breakdowns, and the
            # compressed-wire extras (DESIGN.md §20): the live
            # compression ratio vs an f32 wire and the error-feedback
            # residual norm.
            for key in ("bytes_out", "bytes_in", "frames_in"):
                val = rec.get(key)
                if val is not None and (
                    not isinstance(val, int) or isinstance(val, bool)
                    or val < 0
                ):
                    _fail(
                        f"wire.{key} must be a non-negative int or "
                        f"null, got {val!r}"
                    )
            for key in ("encode_s", "decode_s", "compression_ratio",
                        "ef_residual_norm"):
                val = rec.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"wire.{key} must be a number or null, got {val!r}"
                    )
            for key in ("planes", "schemes"):
                d = rec.get(key)
                if d is not None:
                    if not isinstance(d, dict) or not all(
                        isinstance(v, dict) and all(
                            _is_num(x) for x in v.values()
                        )
                        for v in d.values()
                    ):
                        _fail(
                            f"wire.{key} must map names to numeric byte "
                            f"objects, got {d!r}"
                        )
        elif rec.get("event") == "autoscale":
            # v6: one elastic-membership action (DESIGN.md §15).
            if rec.get("action") not in ("spawn", "retire"):
                _fail(
                    f"autoscale.action must be 'spawn' or 'retire', "
                    f"got {rec.get('action')!r}"
                )
            for key in ("rank", "active"):
                val = rec.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"autoscale.{key} must be a non-negative int, "
                        f"got {val!r}"
                    )
            for key in ("rate", "target"):
                val = rec.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"autoscale.{key} must be a number or null, "
                        f"got {val!r}"
                    )
        elif rec.get("event") == "membership":
            # v13: one membership change (controlplane — DESIGN.md §22):
            # every failover / split / merge is exactly one epoch bump,
            # and this event is its audit trail.
            if not isinstance(rec.get("action"), str) \
                    or not rec["action"]:
                _fail(
                    f"membership.action must be a non-empty string, "
                    f"got {rec.get('action')!r}"
                )
            ep = rec.get("epoch")
            if ep is not None and (
                not isinstance(ep, int) or isinstance(ep, bool) or ep < 0
            ):
                _fail(
                    f"membership.epoch must be a non-negative int or "
                    f"null (pre-epoch deployment), got {ep!r}"
                )
            ns = rec.get("num_shards")
            if not isinstance(ns, int) or isinstance(ns, bool) or ns < 1:
                _fail(
                    f"membership.num_shards must be a positive int, "
                    f"got {ns!r}"
                )
            for key in ("shard", "step"):
                val = rec.get(key)
                if val is not None and (
                    not isinstance(val, int) or isinstance(val, bool)
                    or val < 0
                ):
                    _fail(
                        f"membership.{key} must be a non-negative int "
                        f"or null, got {val!r}"
                    )
        elif rec.get("event") == "ingest_batch":
            # v15: one bulk push_frames call (batched wire ingest —
            # DESIGN.md §24): frames in, rejects attributed, bytes
            # accepted, and whether the vectorized path actually ran.
            for key in ("shard", "frames", "rejected", "bytes"):
                val = rec.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"ingest_batch.{key} must be a non-negative "
                        f"int, got {val!r}"
                    )
            if rec["rejected"] > rec["frames"]:
                _fail(
                    f"ingest_batch.rejected ({rec['rejected']}) exceeds "
                    f"frames ({rec['frames']})"
                )
            if not isinstance(rec.get("batched"), bool):
                _fail(
                    f"ingest_batch.batched must be a bool, "
                    f"got {rec.get('batched')!r}"
                )
            dur = rec.get("dur_s")
            if not _is_num(dur) or dur < 0:
                _fail(
                    f"ingest_batch.dur_s must be a non-negative "
                    f"number, got {dur!r}"
                )
            step = rec.get("step")
            if step is not None and (
                not isinstance(step, int) or isinstance(step, bool)
                or step < 0
            ):
                _fail(
                    f"ingest_batch.step must be a non-negative int "
                    f"or null, got {step!r}"
                )
    elif kind == "span":
        # v5: one timed phase of a round (telemetry/trace.py).
        if not isinstance(rec.get("phase"), str) or not rec["phase"]:
            _fail(f"span.phase must be a non-empty string, "
                  f"got {rec.get('phase')!r}")
        for key in ("t_wall", "dur_s"):
            if not _is_num(rec.get(key)):
                _fail(f"span.{key} must be a number, got {rec.get(key)!r}")
        if rec["dur_s"] < 0:
            _fail(f"span.dur_s must be non-negative, got {rec['dur_s']!r}")
        step = rec.get("step")
        if step is not None and (
            not isinstance(step, int) or isinstance(step, bool) or step < 0
        ):
            _fail(f"span.step must be a non-negative int or null, "
                  f"got {step!r}")
        who = rec.get("who")
        if who is not None and not isinstance(who, str):
            _fail(f"span.who must be a string or null, got {who!r}")
    elif kind == "summary":
        for key in ("steps", "events"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool) or val < 0:
                _fail(f"summary.{key} must be a non-negative int, got {val!r}")
        spans = rec.get("spans")
        if spans is not None and (
            not isinstance(spans, int) or isinstance(spans, bool) or spans < 0
        ):
            _fail(f"summary.spans must be a non-negative int or null, "
                  f"got {spans!r}")
        phases = rec.get("phases")
        if phases is not None:
            # v5: per-phase span digest ({phase: {count/mean_s/...}}).
            if not isinstance(phases, dict):
                _fail(f"summary.phases must be an object, got {phases!r}")
            for pk, pv in phases.items():
                if not isinstance(pv, dict) or not all(
                    _is_num(x) for x in pv.values()
                ):
                    _fail(
                        f"summary.phases[{pk!r}] must map stat names to "
                        f"numbers, got {pv!r}"
                    )
        if rec.get("suspicion") is not None:
            _check_float_list("summary", "suspicion", rec["suspicion"])
        if rec.get("suspicion_decayed") is not None:
            # v7: the windowed (halflife-decayed) score.
            _check_float_list(
                "summary", "suspicion_decayed", rec["suspicion_decayed"]
            )
        dfd = rec.get("defense")
        if dfd is not None:
            # v7: the closed-loop defense digest (hub.defense_stats).
            if not isinstance(dfd, dict):
                _fail(f"summary.defense must be an object, got {dfd!r}")
            for key in ("rounds", "escalations", "deescalations"):
                val = dfd.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"summary.defense.{key} must be a non-negative "
                        f"int, got {val!r}"
                    )
            for key in ("mean_w", "min_w"):
                val = dfd.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"summary.defense.{key} must be a number or "
                        f"null, got {val!r}"
                    )
        dpd = rec.get("data_defense")
        if dpd is not None:
            # v9: the data-plane defense digest (hub.data_defense_stats).
            if not isinstance(dpd, dict):
                _fail(
                    f"summary.data_defense must be an object, got {dpd!r}"
                )
            for key in ("rounds", "flagged"):
                val = dpd.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"summary.data_defense.{key} must be a "
                        f"non-negative int, got {val!r}"
                    )
            for key in ("max_score", "min_w"):
                val = dpd.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"summary.data_defense.{key} must be a number "
                        f"or null, got {val!r}"
                    )
        tgt = rec.get("targeted")
        if tgt is not None:
            # v8: the targeted-eval digest (hub.targeted_stats).
            if not isinstance(tgt, dict):
                _fail(f"summary.targeted must be an object, got {tgt!r}")
            ev = tgt.get("events")
            if not isinstance(ev, int) or isinstance(ev, bool) or ev < 0:
                _fail(
                    f"summary.targeted.events must be a non-negative "
                    f"int, got {ev!r}"
                )
            for key in ("last_confusion", "last_asr"):
                val = tgt.get(key)
                if val is not None and not _is_num(val):
                    _fail(
                        f"summary.targeted.{key} must be a number or "
                        f"null, got {val!r}"
                    )
        for key in ("wire_planes", "wire_schemes"):
            # v6 planes / v11 schemes: the hub's cumulative wire byte
            # breakdowns ({name: {bytes_out, bytes_in}}).
            d = rec.get(key)
            if d is not None:
                if not isinstance(d, dict) or not all(
                    isinstance(v, dict) and all(
                        _is_num(x) for x in v.values()
                    )
                    for v in d.values()
                ):
                    _fail(
                        f"summary.{key} must map names to numeric byte "
                        f"objects, got {d!r}"
                    )
        st = rec.get("step_time")
        if st is not None:
            if not isinstance(st, dict):
                _fail(f"summary.step_time must be an object, got {st!r}")
            for key in ("mean_s", "p50_s", "p95_s", "p99_s"):
                val = st.get(key)
                # v1 summaries carry only mean_s; v2 adds the percentiles
                # — whichever are present must be numbers.
                if key in st and not _is_num(val):
                    _fail(
                        f"summary.step_time.{key} must be a number, "
                        f"got {val!r}"
                    )
        sd = rec.get("staleness")
        if sd is not None:
            # v4: the async plane's digest (hub.staleness_stats).
            if not isinstance(sd, dict):
                _fail(f"summary.staleness must be an object, got {sd!r}")
            for key in ("count", "mean", "max"):
                if not _is_num(sd.get(key)):
                    _fail(
                        f"summary.staleness.{key} must be a number, "
                        f"got {sd.get(key)!r}"
                    )
            hist = sd.get("hist")
            if not isinstance(hist, dict) or not all(
                _is_num(v) for v in hist.values()
            ):
                _fail(
                    f"summary.staleness.hist must map staleness to "
                    f"counts, got {hist!r}"
                )
        fed = rec.get("federated")
        if fed is not None:
            # v10: the federated-round digest (hub.federated_stats).
            if not isinstance(fed, dict):
                _fail(f"summary.federated must be an object, got {fed!r}")
            for key in ("rounds", "budget_exceeded"):
                val = fed.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"summary.federated.{key} must be a non-negative "
                        f"int, got {val!r}"
                    )
            tc = fed.get("top_clients")
            if tc is not None and (
                not isinstance(tc, dict)
                or not all(_is_num(v) for v in tc.values())
            ):
                _fail(
                    f"summary.federated.top_clients must map client ids "
                    f"to numbers, got {tc!r}"
                )
        asd = rec.get("autoscale")
        if asd is not None:
            # v6: the elastic-membership digest (hub.autoscale_stats).
            if not isinstance(asd, dict):
                _fail(f"summary.autoscale must be an object, got {asd!r}")
            for key in ("spawns", "retires", "active_workers"):
                val = asd.get(key)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or val < 0:
                    _fail(
                        f"summary.autoscale.{key} must be a non-negative "
                        f"int, got {val!r}"
                    )
    elif kind == "bench":
        if not isinstance(rec.get("metric"), str):
            _fail(f"bench.metric must be a string, got {rec.get('metric')!r}")
        val = rec.get("value")
        if val is not None and not _is_num(val):
            _fail(f"bench.value must be a number or null, got {val!r}")
        cs = rec.get("chunk_steps")
        if cs is not None and (
            not isinstance(cs, int) or isinstance(cs, bool) or cs < 1
        ):
            _fail(f"bench.chunk_steps must be a positive int, got {cs!r}")
    elif kind == "gar_bench":
        if not isinstance(rec.get("gar"), str):
            _fail(f"gar_bench.gar must be a string, got {rec.get('gar')!r}")
        for key in ("n", "f", "d"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool):
                _fail(f"gar_bench.{key} must be an int, got {val!r}")
        lat = rec.get("latency_s")
        if lat is not None and not _is_num(lat):
            _fail(f"gar_bench.latency_s must be a number or null, got {lat!r}")
        # v12: the --selection micro-mode columns (all optional — plain
        # sweep rows predate them).
        for key in ("grid", "impl"):
            val = rec.get(key)
            if val is not None and not isinstance(val, str):
                _fail(
                    f"gar_bench.{key} must be a string or null, got {val!r}"
                )
        wb = rec.get("wave_buckets")
        if wb is not None and (
            not isinstance(wb, int) or isinstance(wb, bool) or wb < 1
        ):
            _fail(
                f"gar_bench.wave_buckets must be a positive int or null, "
                f"got {wb!r}"
            )
        pb = rec.get("per_bucket_s")
        if pb is not None and not _is_num(pb):
            _fail(
                f"gar_bench.per_bucket_s must be a number or null, got "
                f"{pb!r}"
            )
    elif kind == "hier_bench":
        if not isinstance(rec.get("gar"), str):
            _fail(f"hier_bench.gar must be a string, got {rec.get('gar')!r}")
        for key in ("n", "f", "d", "bucket_size", "levels", "num_buckets"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool):
                _fail(f"hier_bench.{key} must be an int, got {val!r}")
        for key in ("latency_s", "per_client_s"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"hier_bench.{key} must be a number or null, got {val!r}"
                )
        rss = rec.get("peak_rss_bytes")
        if rss is not None and (
            not isinstance(rss, int) or isinstance(rss, bool) or rss < 0
        ):
            _fail(
                f"hier_bench.peak_rss_bytes must be a non-negative int or "
                f"null, got {rss!r}"
            )
    elif kind == "defense_bench":
        # v7: one accuracy cell of the adaptive-attack / closed-loop-
        # defense record (DEFBENCH_r*): which attack faced which rule
        # under which defense, and where the accuracy landed.
        if not isinstance(rec.get("cell"), str) or not rec["cell"]:
            _fail(
                f"defense_bench.cell must be a non-empty string, got "
                f"{rec.get('cell')!r}"
            )
        for key in ("gar",):
            if not isinstance(rec.get(key), str):
                _fail(
                    f"defense_bench.{key} must be a string, got "
                    f"{rec.get(key)!r}"
                )
        atk = rec.get("attack")
        if atk is not None and not isinstance(atk, str):
            _fail(
                f"defense_bench.attack must be a string or null, got {atk!r}"
            )
        dfs = rec.get("defense")
        if dfs is not None and not isinstance(dfs, str):
            _fail(
                f"defense_bench.defense must be a string or null, got {dfs!r}"
            )
        for key in ("n", "f", "steps", "seed"):
            val = rec.get(key)
            if val is not None and (
                not isinstance(val, int) or isinstance(val, bool)
            ):
                _fail(
                    f"defense_bench.{key} must be an int or null, got {val!r}"
                )
        plane = rec.get("plane")
        if plane is not None and not isinstance(plane, str):
            _fail(
                f"defense_bench.plane must be a string or null, got "
                f"{plane!r}"
            )
        for key in ("final_accuracy", "final_loss", "attack_magnitude",
                    "wall_s",
                    # v8: the targeted rows' success metrics; v9 adds
                    # the clean-model trigger-rate floor.
                    "confusion", "asr", "clean_confusion",
                    "asr_baseline"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"defense_bench.{key} must be a number or null, "
                    f"got {val!r}"
                )
        for key in ("suspicion", "suspicion_decayed"):
            val = rec.get(key)
            if val is not None:
                _check_float_list("defense_bench", key, val)
        esc = rec.get("escalations")
        if esc is not None and (
            not isinstance(esc, int) or isinstance(esc, bool) or esc < 0
        ):
            _fail(
                f"defense_bench.escalations must be a non-negative int "
                f"or null, got {esc!r}"
            )
    elif kind == "fed_bench":
        # v10: one FEDBENCH_r* row — a shard-scaling cell (check
        # "scaling"), the S=1 bitwise anchor ("s1_bitwise"), or an
        # autoscaled fleet-rate cell ("fleet").
        if not isinstance(rec.get("check"), str) or not rec["check"]:
            _fail(
                f"fed_bench.check must be a non-empty string, got "
                f"{rec.get('check')!r}"
            )
        for key in ("n", "d", "shards"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool) \
                    or val < 1:
                _fail(
                    f"fed_bench.{key} must be a positive int, got {val!r}"
                )
        if not isinstance(rec.get("gar"), str):
            _fail(f"fed_bench.gar must be a string, got {rec.get('gar')!r}")
        for key in ("population", "f", "rounds", "spawns", "retires",
                    "active_initial", "active_final"):
            val = rec.get(key)
            if val is not None and (
                not isinstance(val, int) or isinstance(val, bool)
                or val < 0
            ):
                _fail(
                    f"fed_bench.{key} must be a non-negative int or "
                    f"null, got {val!r}"
                )
        for key in ("round_s", "round_s_sum", "speedup", "per_client_s",
                    "target_rate", "achieved_rate", "pre_rate",
                    "recovered_rate"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"fed_bench.{key} must be a number or null, got {val!r}"
                )
        for key in ("per_shard_s", "per_shard_rss"):
            val = rec.get(key)
            if val is not None:
                _check_float_list("fed_bench", key, val)
        phases = rec.get("phases")
        if phases is not None:
            # v12: per-phase p50/p95 attribution on scaling rows
            # (ingest/h2d/fold/selection from the trace plane) — the
            # exchange_bench v5 shape, so readers share one parser.
            if not isinstance(phases, dict) or not all(
                isinstance(v, dict) and all(_is_num(x) for x in v.values())
                for v in phases.values()
            ):
                _fail(
                    f"fed_bench.phases must map phases to numeric "
                    f"stat objects, got {phases!r}"
                )
        for key in ("s1_bitwise_equal", "budget_exceeded"):
            val = rec.get(key)
            if val is not None and not isinstance(val, bool):
                _fail(
                    f"fed_bench.{key} must be a bool or null, got {val!r}"
                )
        rss = rec.get("peak_rss_bytes")
        if rss is not None and (
            not isinstance(rss, int) or isinstance(rss, bool) or rss < 0
        ):
            _fail(
                f"fed_bench.peak_rss_bytes must be a non-negative int "
                f"or null, got {rss!r}"
            )
    elif kind == "soak_bench":
        # v13: one SOAKBENCH_r* scenario row — sustained rounds through
        # the federated engine under control-plane stress (steady /
        # rolling_restart / partition / churn), with the trace plane's
        # round-latency percentiles as the SLO columns.
        if not isinstance(rec.get("check"), str) or not rec["check"]:
            _fail(
                f"soak_bench.check must be a non-empty string, got "
                f"{rec.get('check')!r}"
            )
        for key in ("rounds", "d", "shards", "cohort"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool) \
                    or val < 1:
                _fail(
                    f"soak_bench.{key} must be a positive int, got {val!r}"
                )
        for key in ("population", "failovers", "partitions", "resizes",
                    "stale_rejects", "epoch_final", "dropped_total"):
            val = rec.get(key)
            if val is not None and (
                not isinstance(val, int) or isinstance(val, bool)
                or val < 0
            ):
                _fail(
                    f"soak_bench.{key} must be a non-negative int or "
                    f"null, got {val!r}"
                )
        for key in ("p50_s", "p95_s", "p99_s", "mean_s", "wall_s",
                    "kill_cost_rounds"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"soak_bench.{key} must be a number or null, "
                    f"got {val!r}"
                )
        bw = rec.get("bitwise_equal")
        if bw is not None and not isinstance(bw, bool):
            _fail(
                f"soak_bench.bitwise_equal must be a bool or null, "
                f"got {bw!r}"
            )
    elif kind == "trans_bench":
        # v14: one TRANSBENCH_r* row — either an A/B cell (fused twin
        # vs unrolled per-slot reference on a transformer model) or a
        # robustness/backdoor cell (ASR with baseline attribution).
        if not isinstance(rec.get("check"), str) or not rec["check"]:
            _fail(
                f"trans_bench.check must be a non-empty string, got "
                f"{rec.get('check')!r}"
            )
        if not isinstance(rec.get("model"), str) or not rec["model"]:
            _fail(
                f"trans_bench.model must be a non-empty string, got "
                f"{rec.get('model')!r}"
            )
        for key in ("slots", "d"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool) \
                    or val < 1:
                _fail(
                    f"trans_bench.{key} must be a positive int, got "
                    f"{val!r}"
                )
        for key in ("path", "cell", "defense", "backend"):
            val = rec.get(key)
            if val is not None and not isinstance(val, str):
                _fail(
                    f"trans_bench.{key} must be a string or null, got "
                    f"{val!r}"
                )
        for key in ("seq", "heads", "depth", "reps", "trials", "steps"):
            val = rec.get(key)
            if val is not None and (
                not isinstance(val, int) or isinstance(val, bool)
                or val < 0
            ):
                _fail(
                    f"trans_bench.{key} must be a non-negative int or "
                    f"null, got {val!r}"
                )
        for key in ("per_slot_grad_s", "speedup", "asr", "asr_baseline",
                    "accuracy"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"trans_bench.{key} must be a number or null, "
                    f"got {val!r}"
                )
        dg = rec.get("dce_guard")
        if dg is not None and not isinstance(dg, bool):
            _fail(
                f"trans_bench.dce_guard must be a bool or null, got "
                f"{dg!r}"
            )
        rss = rec.get("peak_rss_bytes")
        if rss is not None and (
            not isinstance(rss, int) or isinstance(rss, bool) or rss < 0
        ):
            _fail(
                f"trans_bench.peak_rss_bytes must be a non-negative int "
                f"or null, got {rss!r}"
            )
    elif kind == "transfer_bench":
        for key in ("devices", "d"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool):
                _fail(f"transfer_bench.{key} must be an int, got {val!r}")
        lat = rec.get("latency_s")
        if lat is not None and not _is_num(lat):
            _fail(
                f"transfer_bench.latency_s must be a number or null, "
                f"got {lat!r}"
            )
    elif kind == "exchange_bench":
        for key in ("n", "d"):
            val = rec.get(key)
            if not isinstance(val, int) or isinstance(val, bool):
                _fail(f"exchange_bench.{key} must be an int, got {val!r}")
        if not isinstance(rec.get("wire"), str):
            _fail(
                f"exchange_bench.wire must be a string, got "
                f"{rec.get('wire')!r}"
            )
        phases = rec.get("phases")
        if phases is not None:
            # v5: per-phase span percentiles on scenario / trace-A/B
            # rows — the artifact attributes its speedups, not just
            # reports them.
            if not isinstance(phases, dict) or not all(
                isinstance(v, dict) and all(_is_num(x) for x in v.values())
                for v in phases.values()
            ):
                _fail(
                    f"exchange_bench.phases must map phases to numeric "
                    f"stat objects, got {phases!r}"
                )
        cell = rec.get("cell")
        if cell is not None and not isinstance(cell, str):
            # v11: EXCHBENCH_r05 robustness-matrix cells carry a cell
            # label (scheme x attack) like the DEFBENCH rows do.
            _fail(
                f"exchange_bench.cell must be a string or null, got "
                f"{cell!r}"
            )
        ma = rec.get("matched_accuracy")
        if ma is not None and not isinstance(ma, bool):
            _fail(
                f"exchange_bench.matched_accuracy must be a bool or "
                f"null, got {ma!r}"
            )
        for key in ("round_s", "wire_bytes_per_step", "straggler_ms",
                    "sync_round_s", "async_round_s", "speedup",
                    "trace_off_round_s", "trace_on_round_s",
                    "trace_overhead",
                    # v6: autoscale scenario rates (scaleup/scaledown).
                    "pre_rate", "spike_rate", "recovered_rate",
                    # v11: the compressed-wire robustness cells
                    # (EXCHBENCH_r05) — matched-accuracy check plus the
                    # adaptive-attack headroom instrument.
                    "final_accuracy", "attack_magnitude", "headroom",
                    "compression_ratio"):
            val = rec.get(key)
            if val is not None and not _is_num(val):
                _fail(
                    f"exchange_bench.{key} must be a number or null, "
                    f"got {val!r}"
                )
        for key in ("active_initial", "active_final", "spawns",
                    "retires"):
            # v6: membership counts — integers, not rates.
            val = rec.get(key)
            if val is not None and (
                not isinstance(val, int) or isinstance(val, bool)
                or val < 0
            ):
                _fail(
                    f"exchange_bench.{key} must be a non-negative int "
                    f"or null, got {val!r}"
                )
        lb = rec.get("learn_ms0_bitwise")
        if lb is not None and not isinstance(lb, bool):
            _fail(
                f"exchange_bench.learn_ms0_bitwise must be a bool or "
                f"null, got {lb!r}"
            )
        rss = rec.get("peak_rss_bytes")
        if rss is not None and (
            not isinstance(rss, int) or isinstance(rss, bool) or rss < 0
        ):
            _fail(
                f"exchange_bench.peak_rss_bytes must be a non-negative "
                f"int or null, got {rss!r}"
            )
    # kind == "run": meta payload is free-form (validated as JSON above).
    return rec


def validate_jsonl(path):
    """Validate every line of a JSONL artifact; returns the record count."""
    count = 0
    with open(path) as fp:
        for lineno, line in enumerate(fp, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                _fail(f"{path}:{lineno} is not JSON: {e}")
            try:
                validate_record(rec)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}") from None
            count += 1
    return count


# --- Prometheus text exposition --------------------------------------------


def prometheus_text(hub):
    """Prometheus text-format snapshot of a ``MetricsHub`` (exposition
    format 0.0.4 — what ``GET /metrics`` on apps/demo.py serves)."""
    lines = []

    def metric(name, mtype, help_, samples):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if value is None:
                continue
            label_s = (
                "{" + ",".join(f'{k}="{v}"' for k, v in labels.items()) + "}"
                if labels else ""
            )
            lines.append(f"{name}{label_s} {value:g}")

    c = hub.counters()
    metric("garfield_steps_total", "counter",
           "Training steps folded into the hub.", [({}, c["steps"])])
    metric("garfield_events_total", "counter",
           "Liveness/exchange events folded into the hub.",
           [({}, c["events"])])
    metric("garfield_loss", "gauge", "Last recorded training loss.",
           [({}, c["loss"])])
    metric("garfield_gar_tau", "gauge",
           "cclip clip threshold at the last tapped step (0 for other "
           "rules).", [({}, c["tau"])])
    metric("garfield_gar_clip_fraction", "gauge",
           "Fraction of ranks clipped at the last tapped step.",
           [({}, c["clip_frac"])])
    st = hub.step_time_stats()
    metric("garfield_step_time_seconds", "gauge",
           "Mean recorded step wall time.",
           [({}, None if st is None else st["mean_s"])])
    if st is not None:
        metric("garfield_step_time_seconds_quantile", "gauge",
               "Step wall-time percentiles from the hub's recorded step "
               "times (the dispatch-tail signal --chunk_steps targets).",
               [({"quantile": "0.5"}, st["p50_s"]),
                ({"quantile": "0.95"}, st["p95_s"]),
                ({"quantile": "0.99"}, st["p99_s"])])
    hists = hub.phase_histograms()
    if hists:
        # v5: per-phase round-time attribution (telemetry/trace.py) — a
        # real Prometheus histogram per phase over the span durations,
        # the per-phase twin of the step-time quantiles above (and the
        # latency control signal the autoscaling work needs).
        from .hub import PHASE_BUCKETS

        lines.append(
            "# HELP garfield_phase_seconds Wall time of each traced "
            "round phase (spans, schema v5)."
        )
        lines.append("# TYPE garfield_phase_seconds histogram")
        for phase, h in hists.items():
            cum = 0
            for le in PHASE_BUCKETS:
                cum += h["buckets"].get(le, 0)
                lines.append(
                    f'garfield_phase_seconds_bucket'
                    f'{{phase="{phase}",le="{le:g}"}} {cum}'
                )
            lines.append(
                f'garfield_phase_seconds_bucket'
                f'{{phase="{phase}",le="+Inf"}} {h["count"]}'
            )
            lines.append(
                f'garfield_phase_seconds_sum{{phase="{phase}"}} '
                f'{h["sum"]:g}'
            )
            lines.append(
                f'garfield_phase_seconds_count{{phase="{phase}"}} '
                f'{h["count"]}'
            )
    w = hub.wire_counters()
    if any(w.values()):
        # v11: the scheme-labelled samples (DESIGN.md §20) join the
        # direction-only totals under the same counter — the
        # compressed-wire claim (≥8x bytes/step) auditable live. Sum
        # over {direction=} alone; the {scheme=,direction=} series are
        # the breakdown, not additional traffic.
        wire_samples = [({"direction": "out"}, float(w["bytes_out"])),
                        ({"direction": "in"}, float(w["bytes_in"]))]
        wire_samples += [
            ({"scheme": s, "direction": d}, float(counts["bytes_" + d]))
            for s, counts in hub.wire_scheme_counters().items()
            for d in ("out", "in")
        ]
        metric("garfield_wire_bytes_total", "counter",
               "Wire bytes through the typed host-plane codec "
               "(direction-only totals, plus per-scheme breakdown "
               "series labelled scheme=).",
               wire_samples)
        planes = hub.wire_plane_counters()
        if planes:
            # v6: plane-labelled byte counters (DESIGN.md §15) — the
            # gradient/model/control planes' wire costs attribute
            # separately instead of blurring into the totals.
            metric("garfield_wire_plane_bytes_total", "counter",
                   "Wire bytes per exchange plane (0=control, "
                   "1=gradients, 2=models).",
                   [({"plane": p, "direction": d},
                     float(counts["bytes_" + d]))
                    for p, counts in planes.items()
                    for d in ("out", "in")])
        metric("garfield_wire_codec_seconds_total", "counter",
               "Host seconds spent in the wire codec.",
               [({"op": "encode"}, w["encode_s"]),
                ({"op": "decode"}, w["decode_s"])])
        metric("garfield_send_queue_drops_total", "counter",
               "Publisher-side frames shed to sender-queue overflow "
               "(backpressure; the send-side twin of plane_drop).",
               [({}, float(w["send_queue_drops"]))])
    ib = hub.ingest_batch_stats()
    if ib is not None:
        # v15: the bulk ingest plane (DESIGN.md §24) — host seconds in
        # push_frames split by path, plus the frame/reject totals that
        # say whether the vectorized decode is actually being hit.
        metric("garfield_ingest_batch_seconds", "counter",
               "Host seconds spent in bulk frame ingest (push_frames), "
               "split by whether the vectorized batch decode ran.",
               [({"path": "batched"}, ib["batched_s"]),
                ({"path": "fallback"}, ib["fallback_s"])])
        metric("garfield_ingest_batch_frames_total", "counter",
               "Frames offered to bulk ingest, and the subset rejected "
               "with sender attribution.",
               [({"outcome": "offered"}, float(ib["frames"])),
                ({"outcome": "rejected"}, float(ib["rejected"]))])
    stale = hub.staleness_stats()
    if stale is not None:
        # v4: bounded-staleness async plane (DESIGN.md §14) — a real
        # Prometheus histogram over per-quorum-member staleness in
        # rounds, plus the hard-cutoff tail visible in the +Inf bucket.
        buckets = [0, 1, 2, 4, 8, 16, 32]
        lines.append(
            "# HELP garfield_staleness_rounds Staleness (rounds behind "
            "the PS) of every async-quorum member."
        )
        lines.append("# TYPE garfield_staleness_rounds histogram")
        cum = 0
        for le in buckets:
            cum = sum(
                c for t, c in stale["hist"].items() if t <= le
            )
            lines.append(
                f'garfield_staleness_rounds_bucket{{le="{le}"}} {cum}'
            )
        lines.append(
            f'garfield_staleness_rounds_bucket{{le="+Inf"}} '
            f'{stale["count"]}'
        )
        lines.append(
            f'garfield_staleness_rounds_sum '
            f'{stale["mean"] * stale["count"]:g}'
        )
        lines.append(f'garfield_staleness_rounds_count {stale["count"]}')
        metric("garfield_staleness_rounds_max", "gauge",
               "Largest staleness admitted so far (bounded by "
               "--max_staleness).", [({}, float(stale["max"]))])
    autos = hub.autoscale_stats()
    if autos is not None:
        # v6: the elastic-membership plane (DESIGN.md §15).
        metric("garfield_active_workers", "gauge",
               "Workers currently active under the autoscale controller.",
               [({}, float(autos["active_workers"]))])
        metric("garfield_autoscale_actions_total", "counter",
               "Autoscale membership actions taken.",
               [({"action": "spawn"}, float(autos["spawns"])),
                ({"action": "retire"}, float(autos["retires"]))])
    fed = hub.federated_stats()
    if fed is not None:
        # v10: the federated round engine (DESIGN.md §19).
        metric("garfield_fed_rounds_total", "counter",
               "Federated rounds completed by the sharded round engine.",
               [({}, float(fed["rounds"]))])
        if fed["shards"] is not None:
            metric("garfield_fed_shards", "gauge",
                   "PS shard count of the federated deployment.",
                   [({}, float(fed["shards"]))])
        if fed["last_cohort"] is not None:
            metric("garfield_fed_cohort_size", "gauge",
                   "Active cohort size of the last federated round.",
                   [({}, float(fed["last_cohort"]))])
        metric("garfield_fed_budget_exceeded_total", "counter",
               "Rounds whose realized Byzantine count exceeded the "
               "cohort's priced f budget (simulation audit).",
               [({}, float(fed["budget_exceeded"]))])
        top = hub.client_suspicion_decayed(k=16)
        if top:
            metric("garfield_client_suspicion_decayed", "gauge",
                   "Decayed exclusion frequency of the most-suspect "
                   "sampled clients, keyed by stable GLOBAL client id "
                   "(v10; resampling cannot launder it).",
                   [({"client": str(c)}, float(s))
                    for c, s in sorted(top.items())])
    dfs = hub.defense_stats()
    if dfs is not None:
        # v7: the closed-loop defense (DESIGN.md §16).
        if dfs["level"] is not None:
            metric("garfield_defense_level", "gauge",
                   "Active escalation-ladder level of the closed-loop "
                   "defense.", [({}, float(dfs["level"]))])
        metric("garfield_defense_escalations_total", "counter",
               "Rule-ladder transitions taken by the closed-loop defense.",
               [({"direction": "escalate"}, float(dfs["escalations"])),
                ({"direction": "deescalate"},
                 float(dfs["deescalations"]))])
        if dfs["min_w"] is not None:
            metric("garfield_defense_min_weight", "gauge",
                   "Smallest suspicion weight applied so far.",
                   [({}, float(dfs["min_w"]))])
    dpd = hub.data_defense_stats()
    if dpd is not None:
        # v9: the data-plane defense (DESIGN.md §18) — per-rank spectral
        # outlier scores from the last audited quorum plus the detector
        # counters.
        metric("garfield_dataplane_outlier_score", "gauge",
               "Spectral outlier score of each rank's gradient "
               "fingerprint at the last data-defense round (v9).",
               [({"rank": str(r)}, float(s))
                for r, s in sorted(dpd["scores"].items())])
        metric("garfield_dataplane_flagged_total", "counter",
               "Rank-rounds flagged by the data-plane detectors.",
               [({}, float(dpd["flagged"]))])
        if dpd["min_w"] is not None:
            metric("garfield_dataplane_min_weight", "gauge",
                   "Smallest data-plane suspicion weight applied so far.",
                   [({}, float(dpd["min_w"]))])
    susp = hub.suspicion()
    if susp is not None:
        metric("garfield_rank_suspicion", "gauge",
               "Cumulative exclusion frequency per rank under the active "
               "GAR (the Byzantine-audit signal).",
               [({"rank": str(i)}, float(s)) for i, s in enumerate(susp)])
        if hub._halflife is not None:
            susp_d = hub.suspicion_decayed()
            metric("garfield_rank_suspicion_decayed", "gauge",
                   "Exclusion frequency over the halflife-decayed window "
                   "(v7; the score a rotated cohort cannot launder).",
                   [({"rank": str(i)}, float(s))
                    for i, s in enumerate(susp_d)])
        metric("garfield_rank_observed_total", "counter",
               "Quorum appearances per rank.",
               [({"rank": str(i)}, float(o))
                for i, o in enumerate(hub._observed)])
        metric("garfield_rank_excluded_total", "counter",
               "Cumulative refused influence per rank.",
               [({"rank": str(i)}, float(e))
                for i, e in enumerate(hub._excluded)])
    return "\n".join(lines) + "\n"

"""Experiment: how to compute 8 per-worker ResNet-18 gradients on one chip.

The logical-worker fold (n workers emulated on 1 chip) pays a 36-63% relayout
tax when done with vmap: the 5-D (worker, batch, H, W, C) intermediates get
transposed/sliced between convs (PERF.md "Known frontier", xplane-confirmed).
This script times the candidate structures on the real chip:

  vmap     — round-1 production path (the taxed one)
  unroll   — Python loop over workers: 8 independent 4-D fwd+bwd subgraphs,
             no 5-D tensors anywhere; XLA schedules/interleaves them
  scan     — lax.scan over stacked worker batches (sequential, one program)
  fused200 — single batch-200 fwd+bwd (NOT per-worker semantics: the lower
             bound on compute)

Run from the repo root (no PYTHONPATH — axon gotcha):
  python scripts/experiments/fold_tax.py
"""

import functools
import os
import sys
import time

# Make garfield_tpu importable without PYTHONPATH (which breaks axon plugin
# registration — verify-skill gotcha).
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from garfield_tpu import models
from garfield_tpu.parallel import core
from garfield_tpu.utils import profiling, selectors


def build(variant, num_workers=8, batch=25, model="resnet18"):
    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    module = models.select_model(model, "cifar10", dtype=dtype)
    loss_fn = selectors.select_loss("cross-entropy")
    init_fn, grad_fn, _ = core.make_worker_fns(module, loss_fn)

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((num_workers, batch, 32, 32, 3)), jnp.float32
    )
    y = jnp.asarray(rng.integers(0, 10, (num_workers, batch)), jnp.int32)
    params, ms = init_fn(jax.random.PRNGKey(0), x[0])
    keys = jax.random.split(jax.random.PRNGKey(1), num_workers)

    if variant == "vmap":
        def step(params, ms, x, y):
            g, (loss, _) = jax.vmap(
                grad_fn, in_axes=(None, None, 0, 0, 0)
            )(params, ms, x, y, keys)
            return core.flatten_rows(g), jnp.mean(loss)
    elif variant == "unroll":
        def step(params, ms, x, y):
            flats, losses = [], []
            for w in range(num_workers):
                g, (loss, _) = grad_fn(params, ms, x[w], y[w], keys[w])
                flats.append(ravel_pytree(g)[0])
                losses.append(loss)
            return jnp.stack(flats), jnp.mean(jnp.stack(losses))
    elif variant == "scan":
        def step(params, ms, x, y):
            def body(carry, xs):
                xw, yw, kw = xs
                g, (loss, _) = grad_fn(params, ms, xw, yw, kw)
                return carry, (ravel_pytree(g)[0], loss)
            _, (flats, losses) = jax.lax.scan(body, 0, (x, y, keys))
            return flats, jnp.mean(losses)
    elif variant.startswith("hybrid"):
        # unroll groups x vmap(width) inside: hybrid2 = 4 groups of width 2.
        width = int(variant[len("hybrid"):])
        assert num_workers % width == 0
        def step(params, ms, x, y):
            flats, losses = [], []
            for g0 in range(0, num_workers, width):
                g, (loss, _) = jax.vmap(
                    grad_fn, in_axes=(None, None, 0, 0, 0)
                )(params, ms, x[g0:g0 + width], y[g0:g0 + width],
                  keys[g0:g0 + width])
                flats.append(core.flatten_rows(g))
                losses.append(loss)
            return jnp.concatenate(flats), jnp.mean(jnp.stack(losses))
    elif variant == "fused200":
        def step(params, ms, x, y):
            xf = x.reshape((-1,) + x.shape[2:])
            yf = y.reshape((-1,) + y.shape[2:])
            g, (loss, _) = grad_fn(params, ms, xf, yf, keys[0])
            flat = ravel_pytree(g)[0]
            return jnp.broadcast_to(flat[None], (num_workers, flat.size)), loss
    else:
        raise ValueError(variant)

    # Chain iterations through the seed input so the host-side loop stays
    # ordered, and keep a live (1e-20-scaled, not 0.0 — XLA would constant-
    # fold that and dead-code-eliminate the whole backward) dependency on
    # the gradient stack so nothing is eliminated.
    @jax.jit
    def chained(seed, params, ms, x, y):
        flats, loss = step(params, ms, x, y)
        # Reduce the FULL stack: anything narrower (e.g. flats[:, :8]) lets
        # XLA prune the backward to the few params feeding those columns.
        live = jnp.sum(flats).astype(jnp.float32) * 1e-20
        return jnp.float32(loss) + live + seed * 1e-20

    return chained, (params, ms, x, y)


def time_variant(variant, reps=20, **kw):
    chained, (params, ms, x, y) = build(variant, **kw)
    seed = jnp.float32(0.0)
    out = chained(seed, params, ms, x, y)
    float(out)  # compile + drain

    def timed(k):
        s = jnp.float32(0.0)
        t0 = time.perf_counter()
        for _ in range(k):
            s = chained(s, params, ms, x, y)
        float(s)
        return time.perf_counter() - t0

    dt = profiling.paired_reps(timed, reps)
    return dt


if __name__ == "__main__":
    import sys

    variants = sys.argv[1:] or ["vmap", "unroll", "scan", "fused200"]
    for v in variants:
        dt = time_variant(v)
        ms_ = "below-noise" if dt is None else f"{dt * 1e3:7.2f} ms"
        print(f"{v:>9}: {ms_}", flush=True)

"""Folded attack+GAR fast path: poison the Gram, never the rows.

The round-3 profiling conclusion (PERF.md "Known frontier") was that ANY
gradient attack costs ~4.5 ms/step on the north-star krum+lie config because
the whole-tree ``where`` rewrite forces the stacked gradient tree to
materialize and breaks the Gram/weighted-sum-into-backward fusion the
fault-free step enjoys. This module removes that structural tax for the
deterministic attacks by exploiting their row-level algebra
(``attacks.plan_gradient_attack_fold``):

  poisoned row i == row_scale[i] * extended_stack[row_map[i]]

where ``extended_stack`` is the raw stack plus at most one shared fake row
(lie's mu + z*sigma / empire's -eps*mu, byzWorker.py:108-143 — every
colluding Byzantine publishes the SAME vector). Consequently

  poisoned_gram = (scale outer scale) * raw_gram[row_map][:, row_map]

is a static remap of the raw ``(n+1, n+1)`` Gram — computed with ONE extra
row in the per-leaf Gram matmuls that fuse into the backward epilogue
exactly like the fault-free step — and the GAR's selection average is one
weighted row sum over the extended stack. Nothing attack-shaped ever touches
the (n, d)-sized data path.

Measured on the v5e chip (same-process paired-reps, ResNet-18/CIFAR-10, 8
workers, krum f=2 under lie, bf16 pipeline): 14.4-14.7 -> 12.4-12.6 ms/step
(1.16x), within 0.6 ms of the fault-free step — where four round-2/3
attempts that still wrote poisoned rows (elementwise where, row scatter,
contiguous DUS, flat-path algebraic folding) all measured within noise of
each other (PERF.md).

Applies when the topology's tree path is eligible, the attack is
deterministic (lie/empire/reverse/crash), and the rule exposes a
fold-capable interface: ``gram_select`` (krum, average),
``fold_aggregate`` (Bulyan), ``tree_aggregate_ext`` (the coordinate-wise
median/tmean — their Pallas kernels apply the row remap/scale
in-register, ops/coordinate.py), or ``fold_flat_aggregate`` (cclip —
the remap applies to per-row scalars of its iterations, r5). Randomized
attacks (random/drop) keep the ``where`` tree path. Zero-scale rows
(the crash attack) are sanitized everywhere a 0*inf could otherwise
produce NaN: the remapped Gram's zero-scale rows/cols are forced to
exact zeros (matching the where-path's literal zero row, whose inner
products are exactly 0 even when the raw gradient is non-finite), the
weighted sums already mask zero-weight rows (``tree_weighted_sum`` /
``apply_rows``'s ``used`` guard), and the coordinate-wise kernels
special-case zero scales in-register — so folded selection equals
where-path selection even with non-finite raw gradients (ADVICE r4;
asserted in tests/test_fold.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..aggregators._common import tree_gram, tree_weighted_sum
from ..attacks import plan_gradient_attack_fold, plan_model_attack_fold

__all__ = [
    "plan_for",
    "plan_for_model",
    "folded_tree_aggregate",
    "folded_tree_aggregate_multi",
]


def plan_for(gar, attack, byz_mask, attack_params):
    """Single-sourced fold eligibility gate for the topology builders
    (aggregathor, byzsgd AND learn): a plan exists iff the rule has a
    fold-capable form (``gram_select``, ``fold_aggregate``, or the
    coordinate-wise ``tree_aggregate_ext``) and the attack folds
    (deterministic, with actual Byzantine slots, and GARFIELD_NO_FOLD
    unset). ``byz_mask`` may be any array-like; it must be concrete (the
    plan is static)."""
    if (gar.gram_select is None and gar.fold_aggregate is None
            and gar.tree_aggregate_ext is None
            and gar.fold_flat_aggregate is None):
        return None
    return plan_gradient_attack_fold(
        attack, np.asarray(byz_mask, dtype=bool), **attack_params
    )


def plan_for_model(gar, attack, byz_mask, attack_params):
    """Fold gate for MODEL-plane exchanges (LEARN gossip, ByzSGD gather).

    The deterministic model attacks (byzServer.py:93-98 reverse, the crash
    fault) are pure per-row scalings — no cohort statistics, no shared fake
    row — so their plan is an identity row map with scales and the same
    Gram-remap machinery applies. Randomized model attacks (random, drop)
    have no folded form and keep the where-path."""
    if (gar.gram_select is None and gar.fold_aggregate is None
            and gar.tree_aggregate_ext is None
            and gar.fold_flat_aggregate is None):
        return None
    return plan_model_attack_fold(
        attack, np.asarray(byz_mask, dtype=bool), **attack_params
    )


def _sanitize_gram(gram_p, row_scale):
    """Force zero-scale (crash) rows/cols of a remapped Gram to exact
    zeros. scale==0 means the poisoned row IS the zero vector, whose
    inner products are exactly 0 — but 0 * inf = NaN if the raw row the
    remap points at is non-finite, which the where-path cannot produce
    (its literal zero row dots finitely). Static no-op when no scale is
    zero, so lie/empire/reverse pay nothing."""
    zero = np.asarray(row_scale) == 0
    if not zero.any():
        return gram_p
    zmask = jnp.asarray(zero)
    return jnp.where(zmask[:, None] | zmask[None, :], 0.0, gram_p)


def folded_tree_aggregate(gar, plan, stacked_tree, *, f, key=None,
                          gar_params=None, subset_sel=None,
                          row_weights=None, return_weights=False):
    """Aggregate a stacked gradient TREE under a folded attack plan.

    Args:
      gar: a registered GAR exposing ``gram_select`` or ``fold_aggregate``.
      plan: ``attacks.GradientAttackFold`` (static row_map/row_scale +
        optional shared fake-row builder).
      stacked_tree: raw per-worker gradients, leading n axis per leaf.
      f: declared tolerance (static).
      key: PRNG key forwarded to the rule (condense's mask; the Gram-form
        rules draw no randomness).
      gar_params: rule hyper-parameters (e.g. krum's ``m``).
      subset_sel: optional (q,) dynamic row indices — the wait-n-f subset
        (server.py:134-155) COMPOSED with the fold: supported for
        ``gram_select`` rules only, where subsetting is a (q, q) gather of
        the remapped Gram plus a weight scatter — no per-leaf row gathers,
        so the async emulation keeps the fast path (VERDICT r4 #5).
      row_weights: optional (n,) per-row scalars (may be traced) COMPOSED
        with the fold — the bounded-staleness discount
        (``utils.rounds.staleness_weights``, DESIGN.md §14). A weighted
        poisoned row is ``(w_i * row_scale_i) * ext[row_map[i]]``, i.e.
        exactly the fold's own row-scale algebra, so the weights multiply
        into the remapped Gram (outer product) and the selection weights
        without the rows ever materializing — ``plan_for`` still applies.
        Supported for ``gram_select`` rules only (the other fold forms
        consume row VALUES; topologies route weighted aggregation there
        through the flat path). Weights must be strictly positive (the
        hard cutoff excludes rows BEFORE the fold; a traced zero weight
        would defeat the static crash-row sanitization).

      return_weights: also return the rule's (n,) selection weights (the
        ``gram_select`` output, scattered to the n logical ranks on the
        subset path) — the feedback signal the adaptive-adversary and
        closed-loop-defense carries consume (DESIGN.md §16) without a
        second selection pass. Supported for ``gram_select`` rules only.

    Returns the aggregated gradient tree (no leading axis) — identical in
    exact arithmetic to ``gar.tree_aggregate(where-poisoned tree)``; with
    ``return_weights``, the tuple ``(tree, weights)``.

    Two layouts, each the measured winner for its rule family (PERF.md r4):

      - ``gram_select`` rules (krum, average) consume the stack only via
        Gram + one weighted row sum, both of which decompose per leaf — the
        extended stack stays a TREE and the per-leaf Grams fuse into the
        backward epilogue;
      - ``fold_aggregate`` rules (Bulyan) need a flat stack for the
        selection matmul and the fused phase-2 kernel anyway, and per-leaf
        Grams measured SLOWER here — so the stack is concatenated ONCE and
        the extension is assembled in BLOCK form (raw Gram, cross-dots c,
        |a|^2) without ever materializing an (n+1, d) array.
    """
    leaves, treedef = jax.tree.flatten(stacked_tree)
    n = leaves[0].shape[0]
    if subset_sel is not None and gar.gram_select is None:
        raise ValueError(
            "subset_sel composes with gram_select rules only (the "
            "coordinate-wise / iterative folds need row values, where a "
            "dynamic subset would force per-leaf gathers — topologies "
            "route those to the flat path instead)"
        )
    if row_weights is not None and gar.gram_select is None:
        raise ValueError(
            "row_weights (the staleness discount) composes with "
            "gram_select rules only — other fold forms consume row "
            "values; topologies route weighted aggregation there through "
            "the flat path"
        )
    if return_weights and gar.gram_select is None:
        raise ValueError(
            "return_weights needs a gram_select rule: only its selection "
            "is one (n,) weight vector (the other fold forms compose "
            "multi-row reductions) — the adaptive/defense carries route "
            "other rules through the where-path's tap recomputation"
        )
    params = dict(gar_params or {})
    # Carried center (stateful rules, cclip): arrives as a params-shaped
    # TREE from TrainState.gar_state; only the flat-iteration branch
    # consumes it (as the concatenated vector).
    center_tree = params.pop("center", None)

    def sanitize_gram(gram_p):
        """See ``_sanitize_gram`` — closure over this plan's scales."""
        return _sanitize_gram(gram_p, plan.row_scale)

    if gar.gram_select is not None or gar.tree_aggregate_ext is not None:
        ext = stacked_tree
        if plan.build_extra is not None:
            extra = plan.build_extra(stacked_tree)
            ext = jax.tree.map(
                lambda l, e: jnp.concatenate([l, e[None]], axis=0),
                stacked_tree, extra,
            )
        if gar.gram_select is None:
            # Coordinate-wise rules (median, tmean): per-leaf kernels with
            # the remap applied in-register — no poisoned stack, no
            # cohort-moment passes outside the fake-row build.
            return gar.tree_aggregate_ext(
                ext, plan.row_map, plan.row_scale, f=f, key=key, **params
            )
        rmap = plan.row_map
        scale = jnp.asarray(plan.row_scale)
        if row_weights is not None:
            # Staleness composition (DESIGN.md §14): per-row weights are
            # row scales, so they fold into the SAME algebra the attack
            # plan uses — the Gram remap below and the weighted sum both
            # see the composed scale and nothing row-shaped materializes.
            scale = scale * jnp.asarray(row_weights, scale.dtype)
        scale_outer = scale[:, None] * scale[None, :]
        gram = tree_gram(ext)  # (n+k, n+k), fuses into the backward like f=0
        gram_p = sanitize_gram(gram[rmap][:, rmap] * scale_outer)
        if subset_sel is not None:
            w_sub = gar.gram_select(
                gram_p[subset_sel][:, subset_sel], f=f, key=key, **params
            )
            w = jnp.zeros((n,), jnp.float32).at[subset_sel].set(w_sub)
        else:
            w = gar.gram_select(gram_p, f=f, key=key, **params)
        sel_w = w.astype(jnp.float32)  # raw selection, pre row-scale
        w = sel_w * scale
        w_ext = jnp.zeros((n + plan.num_extra,), jnp.float32).at[rmap].add(w)
        out = tree_weighted_sum(ext, w_ext)
        return (out, sel_w) if return_weights else out

    if gar.fold_flat_aggregate is not None:
        # Iterative row-value rules (cclip): the rule needs actual row
        # values every iteration, so the EXTENDED stack is materialized
        # once (concat-first, like Bulyan's layout) and the remap/scale is
        # applied to row-level scalars inside the rule — still no poisoned
        # stack, no per-iteration attack passes.
        from ..aggregators._common import concat_stack, unflatten_vec

        stack, shapes = concat_stack(leaves)
        if plan.build_extra is not None:
            extra = plan.build_extra(stacked_tree)
            a_flat = jnp.concatenate(
                [l.reshape(-1) for l in jax.tree.leaves(extra)]
            )
            stack = jnp.concatenate(
                [stack, a_flat[None].astype(stack.dtype)], axis=0
            )
        center = None
        if center_tree is not None:
            center = jnp.concatenate(
                [l.reshape(-1) for l in jax.tree.leaves(center_tree)]
            )
        vec = gar.fold_flat_aggregate(
            stack, plan.row_map, plan.row_scale, f=f, key=key,
            center=center, **params,
        )
        return unflatten_vec(vec, treedef, shapes)

    # fold_aggregate rules: flat-block layout.
    from ..aggregators._common import concat_stack, unflatten_vec

    rmap = plan.row_map
    scale = jnp.asarray(plan.row_scale)
    scale_outer = scale[:, None] * scale[None, :]
    stack, shapes = concat_stack(leaves)
    acc = jnp.promote_types(stack.dtype, jnp.float32)
    gram = jnp.matmul(stack, stack.T, preferred_element_type=acc)
    a_flat = None
    if plan.build_extra is not None:
        extra = plan.build_extra(stacked_tree)
        a_flat = jnp.concatenate(
            [l.reshape(-1) for l in jax.tree.leaves(extra)]
        )
        c = jnp.matmul(stack, a_flat, preferred_element_type=acc)  # <g_i, a>
        aa = jnp.dot(a_flat, a_flat, preferred_element_type=acc)
        gram = jnp.concatenate([
            jnp.concatenate([gram, c[:, None]], axis=1),
            jnp.concatenate([c[None, :], aa[None, None]], axis=1),
        ], axis=0)  # (n+1, n+1), no (n+1, d) array ever built
    gram_p = sanitize_gram(gram[rmap][:, rmap] * scale_outer)

    def apply_rows(W):
        """(r, n) selection weights -> (W @ poisoned_stack, unflatten)."""
        r = W.shape[0]
        W_s = W.astype(jnp.float32) * scale[None, :]
        W_ext = jnp.zeros((r, n + plan.num_extra), jnp.float32).at[
            :, rmap
        ].add(W_s)
        used = jnp.any(W_ext != 0, axis=0)
        selected = jnp.matmul(
            W_ext[:, :n].astype(stack.dtype),
            jnp.where(used[:n, None], stack, 0),
        )
        if a_flat is not None:
            a_safe = jnp.where(used[n], a_flat, 0)  # NaN fake x 0 weight
            selected = selected + jnp.outer(
                W_ext[:, n].astype(stack.dtype), a_safe
            )
        return selected, lambda vec: unflatten_vec(vec, treedef, shapes)

    return gar.fold_aggregate(gram_p, apply_rows, f=f, key=key, **params)


def folded_tree_aggregate_multi(gar, plan, stacked_tree, *, f, keys=None,
                                gar_params=None, subset_sels=None,
                                row_weights=None):
    """Per-OBSERVER folded aggregation: m wait-n-f views of ONE exchange.

    The decentralized topologies (LEARN phases 2/3/5, ByzSGD's model
    plane) have every local observer slot aggregate its OWN seeded
    q-subset of the same gathered stack. For ``gram_select`` rules that
    is m sub-Gram selections of a SINGLE extension + Gram build — the
    expensive (n, d)-shaped work (fake-row moments, per-leaf Gram
    matmuls) is paid once, and each observer adds only a (q, q) gather
    of the tiny Gram plus one weight row. The weighted sums batch into
    one (m, rows) matmul per leaf.

    Args:
      plan: ``GradientAttackFold`` for a deterministic attack, or None for
        the identity fold (no attack, or a randomized attack already
        applied to the tree via the where-path).
      keys: optional (m,) stacked PRNG keys, one per observer (the
        Gram-form rules draw no randomness, but the key rides through for
        signature parity with the flat path).
      subset_sels: (m, q) per-observer row indices, or None for full
        participation (every observer sees all n rows — m identical
        selections, still one Gram).
      row_weights: optional (n,) per-row scalars (may be traced) COMPOSED
        with the fold exactly as in ``folded_tree_aggregate`` — the
        bounded-staleness discount (``utils.rounds.staleness_weights``,
        DESIGN.md §15): a row's staleness is a property of its PUBLISHER,
        so one weight vector is shared by every observer, multiplying
        into the remapped Gram and the per-observer weight rows through
        the fold's own row-scale algebra.

    Returns the aggregated tree with a leading m axis. Rows non-finite in
    the raw stack are handled exactly as ``apply_rows``: a row selected
    by NO observer is masked out of the contraction; the Gram-form rules'
    +inf-distance guard keeps non-finite rows out of every selection, so
    this matches the per-observer where-path.
    """
    if gar.gram_select is None:
        raise ValueError(
            "folded_tree_aggregate_multi needs a gram_select rule (the "
            "per-observer sub-Gram composition; other fold forms need row "
            "values per observer — topologies route those to the flat path)"
        )
    leaves, treedef = jax.tree.flatten(stacked_tree)
    n = leaves[0].shape[0]
    params = dict(gar_params or {})
    params.pop("center", None)  # gram_select rules are stateless
    if plan is None:
        rmap = np.arange(n)
        scale_np = np.ones(n, np.float32)
        build_extra, num_extra = None, 0
    else:
        rmap, scale_np = plan.row_map, plan.row_scale
        build_extra, num_extra = plan.build_extra, plan.num_extra
    ext = stacked_tree
    if build_extra is not None:
        extra = build_extra(stacked_tree)
        ext = jax.tree.map(
            lambda l, e: jnp.concatenate([l, e[None]], axis=0),
            stacked_tree, extra,
        )
    scale = jnp.asarray(scale_np)
    if row_weights is not None:
        # Staleness composition (DESIGN.md §15): per-row weights are row
        # scales, so they multiply into the same algebra the attack plan
        # uses — the remapped Gram below and every observer's weight row
        # see the composed scale; nothing row-shaped materializes.
        scale = scale * jnp.asarray(row_weights, scale.dtype)
    gram = tree_gram(ext)  # (n+k, n+k), ONE build for all observers
    gram_p = _sanitize_gram(
        gram[rmap][:, rmap] * (scale[:, None] * scale[None, :]), scale_np
    )

    def select_one(sel, key):
        if sel is None:
            w = gar.gram_select(gram_p, f=f, key=key, **params)
        else:
            w_sub = gar.gram_select(
                gram_p[sel][:, sel], f=f, key=key, **params
            )
            w = jnp.zeros((n,), jnp.float32).at[sel].set(w_sub)
        return w

    if subset_sels is None:
        if keys is None:
            W = select_one(None, None)[None]
        else:
            W = jax.vmap(lambda k: select_one(None, k))(keys)
    elif keys is None:
        W = jax.vmap(lambda s: select_one(s, None))(subset_sels)
    else:
        W = jax.vmap(select_one)(subset_sels, keys)
    m = W.shape[0]
    W = W.astype(jnp.float32) * scale[None, :]
    W_ext = jnp.zeros((m, n + num_extra), jnp.float32).at[:, rmap].add(W)
    used = jnp.any(W_ext != 0, axis=0)

    def one_leaf(leaf):
        rows = leaf.shape[0]
        flat = leaf.reshape(rows, -1)
        out = jnp.matmul(
            W_ext.astype(leaf.dtype), jnp.where(used[:, None], flat, 0)
        )
        return out.reshape((m,) + leaf.shape[1:])

    out_tree = jax.tree.map(one_leaf, ext)
    if subset_sels is None and keys is None:
        # Full participation, no per-observer keys: ONE selection — return
        # it without the leading axis (the caller broadcasts).
        return jax.tree.map(lambda l: l[0], out_tree)
    return out_tree

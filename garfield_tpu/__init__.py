"""garfield_tpu — a TPU-native framework for Byzantine-resilient distributed SGD.

A ground-up re-design, for TPU hardware, of the capabilities of EPFL DCL's
Garfield library (reference: /root/reference — "Garfield: System Support for
Byzantine Machine Learning", arXiv:2010.05888).

Where the reference builds Byzantine resilience out of multi-process RPC
(torch.distributed.rpc / gRPC) between parameter servers and workers, this
framework expresses the whole worker/server topology as a single SPMD program
over a `jax.sharding.Mesh`:

    grads = per-worker gradients           (shard_map over mesh axis "workers")
    grads = attack(grads, byz_mask, key)   (on-device fault injection)
    stack = all_gather(grads, "workers")   (ICI collective — replaces RPC)
    update = gar(stack, f)                 (robust aggregation, jit'd XLA)
    state = optimizer(state, update)       (replicated => "write_model" is free)

Subpackages
-----------
aggregators : robust Gradient Aggregation Rules (GARs) — the L1 of the
              reference (pytorch_impl/libs/aggregators/).
attacks     : Byzantine gradient/model attack simulators — reference
              byzWorker.py / byzServer.py / attacker.py.
data        : deterministic dataset partitioning — reference datasets.py.
models      : flax model zoo — reference garfieldpp/models/.
parallel    : meshes, SPMD train steps, topologies (SSMW/MSMW/LEARN/CC) —
              reference applications' trainer loops + Garfield_CC.
roles       : Worker/Server/ByzWorker/ByzServer role objects (API parity).
native      : C++ CPU kernels + threadpool (reference libs/native/).
utils       : logging, registries, optimizer/loss selectors — reference
              garfieldpp/tools.py and libs/tools/.
"""

__version__ = "0.1.0"

__all__ = [
    "aggregators",
    "utils",
]

"""Adaptive-adversary vs closed-loop-defense record (DEFBENCH_r*).

The committed acceptance artifact of DESIGN.md §16, measured as matched
accuracy CELLS on the on-mesh aggregathor topology (same task, same
seed, same step budget — only the attack/defense column changes):

  1. ``clean``              — no attack, vanilla krum: the accuracy bar.
  2. ``static-lie``         — the oblivious ALIE attack (z = 1.035).
  3. ``adaptive-lie``       — the suspicion-aware controller
                              (attacks/adaptive.py) against the SAME
                              vanilla krum: the bisection sustains a
                              magnitude far above the static z, so the
                              final accuracy must degrade MORE than the
                              static cell's.
  4. ``adaptive-defense``   — the same adaptive attack against the full
                              closed loop (--defense escalate:
                              suspicion-weighted rows + the
                              krum -> multi-krum -> bulyan ladder,
                              aggregators/defense.py): accuracy must
                              come back to within ``--acc_margin`` of
                              the clean bar.
  5. ``adaptive-rotation``  — the adaptive attack rotating its active
                              cohort over an f_pool = 2f colluder pool:
                              every pool member's DECAYED suspicion must
                              stay below the static-cohort cell's
                              victim — the laundering the windowed
                              score (MetricsHub suspicion_halflife)
                              exists to expose.

Each cell is one ``defense_bench`` record (telemetry schema v7) in the
JSONL twin; the .json artifact adds the derived acceptance verdicts.
Run (CPU container, ~2-4 min):

  python -m garfield_tpu.apps.benchmarks.defense_bench \
      --out DEFBENCH_r01 --num_iter 240
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ... import data as data_lib, parallel
from ...aggregators import defense as defense_lib
from ...attacks import LIE_Z
from ...models import select_model
from ...parallel import aggregathor
from ...telemetry import exporters as tele_fmt, hub as hub_lib
from ...utils import selectors

N_WORKERS = 16
F = 3  # bulyan (the ladder's top) needs n >= 4f + 3 = 15


def _task(args):
    # The default surrogate margin (3.5) is one-shot learnable — every
    # cell saturates and no attack registers in accuracy. The committed
    # record pins a HARD margin (overlapping classes) where a sustained
    # gradient bias measurably moves the decision boundary; an explicit
    # operator env still wins.
    import os

    os.environ.setdefault("GARFIELD_SURROGATE_MARGIN", str(args.margin))
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=args.lr, momentum=0.0, weight_decay=0.0
    )
    m = data_lib.DatasetManager("pima", args.batch, N_WORKERS, N_WORKERS, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    test = parallel.EvalSet(m.get_test_set(), binary=True)
    return module, loss, opt, xs, ys, test


def run_cell(args, task, name, *, attack=None, attack_params=None,
             defense=False, gar="krum"):
    """One accuracy cell: train ``num_iter`` steps, return the record.

    With ``defense`` this drives the SAME closed loop apps/common.py
    deploys: the in-graph suspicion weighting (``defense=`` kwarg) plus
    the host-side escalation policy fed by a MetricsHub's decayed
    suspicion, rebuilding the trainer at level changes (the TrainState
    carries across rebuilds — the ladder is stateful-homogeneous).
    """
    module, loss, opt, xs, ys, test = task
    attack_params = dict(attack_params or {})
    telemetry = defense or bool(args.halflife)
    hub = hub_lib.MetricsHub(
        num_ranks=N_WORKERS, suspicion_halflife=args.halflife,
        meta={"tag": "defense_bench", "cell": name},
    )
    policy = None
    gar_params = {}
    if defense:
        policy = defense_lib.EscalationPolicy(defense_lib.EscalationConfig(
            theta_up=args.theta_up, theta_down=args.theta_down,
            patience=args.patience, clean_window=args.clean_window,
        ))
        if gar in policy.config.levels:
            policy.level = policy.config.levels.index(gar)
        gar, gar_params = policy.current()

    def build(g, gp):
        return aggregathor.make_trainer(
            module, loss, opt, g,
            num_workers=N_WORKERS, f=F,
            attack=attack, attack_params=attack_params,
            gar_params=gp,
            telemetry=telemetry,
            defense=(
                {"halflife": args.halflife or 16.0} if defense else None
            ),
        )

    t0 = time.time()
    init_fn, step_fn, eval_fn = build(gar, gar_params)
    state = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    x = jnp.asarray(xs[:, 0])
    y = jnp.asarray(ys[:, 0])
    escalations = 0
    last_mag = None
    num_batches = xs.shape[1]
    for i in range(args.num_iter):
        b = i % num_batches
        state, metrics = step_fn(
            state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
        )
        if "attack_mag" in metrics:
            last_mag = float(metrics["attack_mag"])
        if telemetry and "tap" in metrics:
            hub.record_step(i, loss=float(metrics["loss"]),
                            tap=jax.device_get(metrics["tap"]))
        if policy is not None:
            susp = hub.suspicion_decayed()
            if susp is not None:
                act = policy.observe(float(
                    defense_lib.suspicion_concentration(susp, F)
                ))
                if act:
                    escalations += 1
                    gar, gar_params = policy.current()
                    print(f"[{name}] step {i}: defense "
                          f"{'escalates' if act > 0 else 'de-escalates'} "
                          f"to {policy.level_name!r}", flush=True)
                    _, step_fn, eval_fn = build(gar, gar_params)
    del x, y
    acc = parallel.compute_accuracy(state, eval_fn, test, binary=True)
    susp = hub.suspicion()
    susp_d = hub.suspicion_decayed()
    rec = tele_fmt.make_record(
        "defense_bench",
        cell=name,
        gar=str(gar),
        attack=attack,
        defense="escalate" if defense else None,
        n=N_WORKERS, f=F,
        steps=int(args.num_iter),
        seed=int(args.seed),
        final_accuracy=round(float(acc), 6),
        attack_magnitude=(
            None if last_mag is None else round(last_mag, 6)
        ),
        escalations=int(escalations) if defense else None,
        suspicion=(
            None if susp is None else np.round(susp, 6).tolist()
        ),
        suspicion_decayed=(
            None if susp_d is None else np.round(susp_d, 6).tolist()
        ),
        wall_s=round(time.time() - t0, 3),
    )
    print(f"[{name}] accuracy {acc:.4f} "
          f"({rec['wall_s']}s, mag={rec['attack_magnitude']})", flush=True)
    return rec


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", type=str, default="DEFBENCH",
                   help="Artifact prefix: writes <out>.json + <out>.jsonl")
    p.add_argument("--num_iter", type=int, default=300)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--margin", type=float, default=1.2,
                   help="Surrogate class margin (GARFIELD_SURROGATE_"
                        "MARGIN default for this run; lower = harder).")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--mag_max", type=float, default=6.0,
                   help="Adaptive bracket ceiling (lie z upper bound).")
    p.add_argument("--halflife", type=float, default=24.0,
                   help="Suspicion halflife (windowed score, schema v7).")
    p.add_argument("--theta_up", type=float, default=0.35)
    p.add_argument("--theta_down", type=float, default=0.1)
    p.add_argument("--patience", type=int, default=4)
    p.add_argument("--clean_window", type=int, default=60)
    p.add_argument("--acc_margin", type=float, default=0.05,
                   help="Defense cell must land within this of clean.")
    p.add_argument("--degrade_margin", type=float, default=0.01,
                   help="Adaptive must undercut static by at least this.")
    args = p.parse_args(argv)

    task = _task(args)
    adaptive_params = {"mag_max": args.mag_max}
    cells = [
        run_cell(args, task, "clean"),
        run_cell(args, task, "static-lie", attack="lie",
                 attack_params={"z": LIE_Z}),
        run_cell(args, task, "adaptive-lie", attack="adaptive-lie",
                 attack_params=adaptive_params),
        run_cell(args, task, "adaptive-defense", attack="adaptive-lie",
                 attack_params=adaptive_params, defense=True),
        run_cell(args, task, "adaptive-rotation", attack="adaptive-lie",
                 attack_params={**adaptive_params, "f_pool": 2 * F,
                                "rotation": 8}),
    ]
    by = {c["cell"]: c for c in cells}
    acc = {k: c["final_accuracy"] for k, c in by.items()}

    # Acceptance verdicts (ISSUE 10): the adaptive attack beats the
    # static one against the vanilla rule; the closed loop restores the
    # bar; rotation launders the cumulative score but NOT the decayed
    # one below the static-cohort victim's.
    pool = list(range(N_WORKERS - 2 * F, N_WORKERS))
    static_cohort = list(range(N_WORKERS - F, N_WORKERS))
    rot_d = by["adaptive-rotation"]["suspicion_decayed"]
    adp_d = by["adaptive-lie"]["suspicion_decayed"]
    rot_max = (
        max(rot_d[r] for r in pool) if rot_d is not None else None
    )
    static_victim = (
        max(adp_d[r] for r in static_cohort) if adp_d is not None else None
    )
    verdicts = {
        "adaptive_beats_static": bool(
            acc["adaptive-lie"]
            <= acc["static-lie"] - args.degrade_margin
        ),
        "defense_restores_bar": bool(
            acc["adaptive-defense"] >= acc["clean"] - args.acc_margin
        ),
        "rotation_launders_decayed_below_static_victim": (
            None if rot_max is None or static_victim is None
            else bool(rot_max < static_victim)
        ),
        "rotation_pool_max_decayed": rot_max,
        "static_cohort_max_decayed": static_victim,
    }
    doc = {
        "bench": "defense_bench",
        "schema_v": tele_fmt.SCHEMA_VERSION,
        "config": {
            "n": N_WORKERS, "f": F, "num_iter": args.num_iter,
            "batch": args.batch, "lr": args.lr, "seed": args.seed,
            "mag_max": args.mag_max, "halflife": args.halflife,
            "theta_up": args.theta_up, "theta_down": args.theta_down,
            "patience": args.patience, "acc_margin": args.acc_margin,
            "degrade_margin": args.degrade_margin,
        },
        "accuracy": acc,
        "verdicts": verdicts,
        "cells": cells,
    }
    with open(args.out + ".json", "w") as fp:
        json.dump(doc, fp, indent=1)
    with open(args.out + ".jsonl", "w") as fp:
        for c in cells:
            tele_fmt.validate_record(c)
            fp.write(json.dumps(c) + "\n")
    print(json.dumps({"accuracy": acc, "verdicts": verdicts}, indent=1))
    ok = all(v for v in (
        verdicts["adaptive_beats_static"],
        verdicts["defense_restores_bar"],
        verdicts["rotation_launders_decayed_below_static_victim"],
    ))
    print(f"defense_bench: {'ACCEPTED' if ok else 'REJECTED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))

"""Bulyan (over Multi-Krum) GAR.

Counterpart of pytorch_impl/libs/aggregators/bulyan.py (:31-84): requires
n >= 4f+3 (:114). Two phases:

1. Selection: n-2f-2 rounds. In round i, each still-active node is scored by
   the sum of its m_i smallest distances to the other active nodes, with
   m_i = min(m, (n-f-2) - i) and m defaulting to n-f-2 (bulyan.py:49-56);
   the round emits the Multi-Krum average of the m_i best-scored active
   gradients (bulyan.py:68) and prunes the single best-scored node.
2. Coordinate-wise averaged median over the n-2f-2 emitted vectors: per
   coordinate, average the beta = (n-2f-2) - 2f values closest to the
   (lower) median (bulyan.py:77-84).

NOTE: the reference's incremental score update after pruning is buggy (it
reads an undefined ``distance[gid]`` and misindexes ``scores[gid]``,
bulyan.py:74-76 — only reached on score ties). This implementation
recomputes scores from the active set each round, which is the intended
semantics and side-steps the bug; equivalence with the reference holds
whenever the reference path is well-defined.

TPU design: one Gram-matmul distance matrix reused across rounds; the
sequential selection is a ``lax.fori_loop`` whose body is masked sort +
prefix-sum + dynamic index — no host sync, compiles to a single XLA while
loop (the reference needed its largest CUDA kernel here, py_bulyan/bulyan.cu).
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import (
    as_stack,
    concat_stack,
    distances_from_gram,
    num_gradients,
    pairwise_distances,
    unflatten_vec,
)
from ..ops import coordinate as _coord


def _selection_weight_matrix(dist, n, f, m, dtype, use_sortnet=None):
    """Phase-1 selection as a (rounds, n) weight matrix.

    The selection loop only needs the (n, n) distance matrix: each round
    scores the active nodes, records the Multi-Krum selection *weights*
    (1/m_i on the m_i best, 0 elsewhere), and prunes the best node. The
    selected averages are then weight matmuls after the loop — the loop
    never touches the d-sized data, so the whole phase costs a single MXU
    pass over the stack (flat) or one matmul per leaf (tree).

    Sortnet path (``use_sortnet=True``, n <= MAX_SORT_N): the round body's
    row sort and stable argsort both run on the odd-even network —
    bitwise-equal (same NaN-last total order, strict-< stable ties; the
    masked matrix carries only finite values and +inf, never NaN). Unlike
    krum, this is OPT-IN rather than env-default: the fori_loop re-sorts
    the masked n x n matrix every round, so the network's O(n^2) exchange
    rounds compound — SELBENCH_r01 measured it slower than the XLA sort
    at every bucket size (265.61 vs 103.46 us/bucket at n=16, 7950.73 vs
    1039.06 at n=32). GARFIELD_SORTNET_SELECT therefore does not reach
    this loop; pass ``use_sortnet=True`` to A/B it (gar_bench --selection
    does).
    """
    m_max = n - f - 2
    rounds = n - 2 * f - 2
    sortnet = use_sortnet is True and n <= _coord.MAX_SORT_N

    def round_body(i, carry):
        active, weights = carry
        m_i = jnp.minimum(m, m_max - i)
        pair_ok = active[:, None] & active[None, :]
        masked = jnp.where(pair_ok, dist, jnp.inf)
        sorted_rows = (
            _coord.sortnet_sort(masked, axis=1) if sortnet
            else jnp.sort(masked, axis=1)
        )
        csum = jnp.cumsum(sorted_rows, axis=1)
        scores = jax.lax.dynamic_index_in_dim(csum, m_i - 1, axis=1, keepdims=False)
        scores = jnp.where(active, scores, jnp.inf)
        # stable: ties break on lowest index
        order = (
            _coord.sortnet_argsort(scores, axis=0) if sortnet
            else jnp.argsort(scores)
        )
        w = jnp.zeros((n,), dtype).at[order].set(
            (jnp.arange(n) < m_i).astype(dtype) / m_i
        )
        weights = weights.at[i].set(w)
        active = active.at[order[0]].set(False)
        return active, weights

    active0 = jnp.ones((n,), dtype=bool)
    weights0 = jnp.zeros((rounds, n), dtype=dtype)
    _, weights = jax.lax.fori_loop(0, rounds, round_body, (active0, weights0))
    return weights


def aggregate(gradients, f, m=None, use_sortnet=None, **kwargs):
    """Bulyan over Multi-Krum."""
    g = as_stack(gradients)
    n, d = g.shape
    if m is None:
        m = n - f - 2
    rounds = n - 2 * f - 2
    dist = pairwise_distances(g)  # (n, n), diag/non-finite -> +inf
    weights = _selection_weight_matrix(dist, n, f, m, g.dtype, use_sortnet)
    # Rows never selected in any round must not poison the matmul with
    # NaN/Inf coordinates (0 * inf = nan); rows that are selected pass
    # through untouched (reference mean semantics).
    used = jnp.any(weights != 0, axis=0)
    selected = weights @ jnp.where(used[:, None], g, 0)  # (rounds, d)

    # Coordinate-wise averaged median (bulyan.py:77-84); fused Pallas kernel
    # on TPU (garfield_tpu/ops/coordinate.py); off the Pallas path the
    # gather-free threshold formulation (averaged_median_mean_xla), so
    # n > MAX_SORT_N degrades gracefully instead of hitting the
    # catastrophic sort+argsort+gather.
    from .. import ops

    beta = rounds - 2 * f
    return ops.averaged_median_mean(selected, beta)


def _select_and_phase2(stack, weights, treedef, shapes, beta):
    """Shared tail of the tree/folded paths: ONE selection matmul over the
    concatenated stack, ONE fused phase-2 kernel, slice back per leaf.

    Per-leaf (rounds, n) @ (n, size) matmuls were measured to eat the whole
    tree-path win at ResNet-18 scale (62 launches, each padded to the MXU
    tile) and per-leaf phase-2 kernels likewise; the single-concat form is
    the bucket-all layout that measured fastest (PERF.md round 4).
    """
    from .. import ops

    used = jnp.any(weights != 0, axis=0)
    selected = jnp.matmul(
        weights.astype(stack.dtype), jnp.where(used[:, None], stack, 0)
    )  # (rounds, d)
    return unflatten_vec(
        ops.averaged_median_mean(selected, beta), treedef, shapes
    )


def tree_aggregate(grads_tree, f, m=None, use_sortnet=None, **kwargs):
    """Tree-mode Bulyan: concat-first.

    Unlike Krum (whose Gram + weighted-sum both decompose per leaf and fuse
    into the backward), Bulyan's selection MATMUL and fused phase-2 kernel
    want one flat stack anyway — and per-leaf Grams measured SLOWER than a
    single flat Gram here (PERF.md round 4). So the tree twin's job is only
    to build that stack cheaply: ONE axis-1 concat of the reshaped stacked
    leaves (measured faster than the flat path's vmapped ravel_pytree) and
    a sliced unflatten of the result.
    """
    leaves, treedef = jax.tree.flatten(grads_tree)
    n = leaves[0].shape[0]
    if m is None:
        m = n - f - 2
    rounds = n - 2 * f - 2
    beta = rounds - 2 * f
    stack, shapes = concat_stack(leaves)
    dist = pairwise_distances(stack)
    weights = _selection_weight_matrix(dist, n, f, m, jnp.float32, use_sortnet)
    return _select_and_phase2(stack, weights, treedef, shapes, beta)


def fold_aggregate(gram_p, apply_rows, f, m=None, use_sortnet=None, **kwargs):
    """Folded-attack Bulyan (parallel.fold): phase 1 runs on the poisoned
    Gram (a static remap of the raw extended Gram — the rows are never
    rewritten); ``apply_rows`` materializes the per-round selected averages
    as one remapped weight matmul over the concatenated extended stack, and
    phase 2 is one fused kernel over the resulting (rounds, d)."""
    from .. import ops

    n = gram_p.shape[0]
    if m is None:
        m = n - f - 2
    rounds = n - 2 * f - 2
    beta = rounds - 2 * f
    dist = distances_from_gram(gram_p)
    weights = _selection_weight_matrix(dist, n, f, m, jnp.float32, use_sortnet)
    selected, unflatten = apply_rows(weights)  # (rounds, d)
    return unflatten(ops.averaged_median_mean(selected, beta))


def check(gradients, f, m=None, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 4 * f + 3:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 3) // 4}"
        )
    if m is not None and (not isinstance(m, int) or m < 1 or m > n - f - 2):
        return (
            f"invalid number of selected gradients, got m = {m!r}, "
            f"expected 1 <= m <= {n - f - 2}"
        )
    return None


def upper_bound(n, f, d):
    """Same bound as (Multi-)Krum (bulyan.py:117-126)."""
    return 1 / math.sqrt(
        2 * (n - f + f * (n + f * (n - f - 2) - 2) / (n - 2 * f - 2))
    )


register("bulyan", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate, fold_aggregate=fold_aggregate)

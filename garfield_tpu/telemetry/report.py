"""Cross-process run report: merge per-role traces, explain the rounds.

``python -m garfield_tpu.telemetry.report RUN_DIR`` consumes the
per-role ``<who>.telemetry.jsonl`` streams a ``--telemetry --trace``
cluster run writes (or a single ``telemetry.jsonl`` from an on-mesh
run) and emits the two artifacts that make the span plane worth having:

  1. **Chrome trace-event JSON** (``trace.json``): every span as an
     ``X`` event, one process lane per role, one thread lane per
     recorded ``tid`` (the exchange waiter threads' eager decode+H2D
     shows up OVERLAPPING the main loop's quorum wait — the PR-4
     concurrency, finally visible). Open in Perfetto or
     chrome://tracing.
  2. **Markdown run report** (``report.md``): per-role per-phase
     p50/p95/p99, per-round critical-path attribution on the reference
     role (phases sum to the measured round time; the residual is
     untraced host glue), a straggler ranking from cross-process
     publish lateness cross-checked against MetricsHub suspicion, and
     the async plane's stale-frame reuse rate.

Clock model. Each span records its wall-clock START (``t_wall``,
``time.time()``) and a MONOTONIC duration (``dur_s``). Durations are
exact per process; cross-process placement needs the processes' wall
clocks reconciled. The merger estimates one offset per role against
the reference role (the PS) from **round-tag anchors** — causal
constraints every round provides:

  - a worker cannot finish receiving round i's model before the PS
    began publishing it:   ``off >= ps_broadcast_start(i) - recv_end(i)``
  - the PS cannot finish round i's quorum before the worker finished
    publishing its round-i gradient: ``off <= ps_quorum_end(i) - pub_end(i)``

The median lower/upper bounds over all shared rounds bracket the
offset; 0 is used when admissible (co-located processes share a
clock), else the bracket midpoint. The bracket width is the report's
quoted **alignment error** — cross-process claims tighter than that
are not supported by the data, and the per-round critical-path check
is asserted only within it.

Everything here is stdlib + the exporters' schema — no jax — and the
output is DETERMINISTIC for a fixed input (pinned on the committed
fixture by tests/test_trace.py): sorted keys, stable ordering, no
wall-clock-of-now anywhere.
"""

import argparse
import json
import os
import statistics
import sys

__all__ = ["load_run", "build", "chrome_trace", "render_markdown", "main"]

# Role-level phases that belong to the main loop's round accounting.
# Exchange-internal spans (publish/collect/decode/gather/latest_wait)
# nest inside them or live on waiter threads; the critical path keeps
# OUTERMOST same-thread spans only, so listing the role vocabulary here
# is documentation, not a filter.
_RECV_PHASES = ("latest_wait", "model_gather", "model_wait")
_PUB_PHASES = ("publish",)


def _percentile(sorted_vals, p):
    """Nearest-rank percentile on a pre-sorted list (deterministic,
    no numpy — the report must run anywhere the artifacts land)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def load_run(paths):
    """Parse telemetry JSONL streams into per-role dicts:
    {role: {spans, events, summary, meta}}. ``paths`` is a directory
    (every ``*.jsonl`` inside) or an explicit list of files."""
    if isinstance(paths, (str, os.PathLike)):
        d = str(paths)
        if os.path.isdir(d):
            paths = sorted(
                os.path.join(d, f) for f in os.listdir(d)
                if f.endswith(".jsonl")
            )
        else:
            paths = [d]
    roles = {}
    for path in paths:
        stem = os.path.basename(path)
        for suffix in (".telemetry.jsonl", ".jsonl"):
            if stem.endswith(suffix):
                stem = stem[: -len(suffix)]
                break
        role = {"spans": [], "events": [], "summary": None, "meta": {},
                "steps": []}
        with open(path) as fp:
            for line in fp:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind == "span":
                    role["spans"].append(rec)
                elif kind == "event":
                    role["events"].append(rec)
                elif kind == "summary":
                    role["summary"] = rec
                elif kind == "run":
                    role["meta"] = rec.get("meta") or {}
                elif kind == "step":
                    role["steps"].append(rec)
        name = role["meta"].get("tag") or (
            role["spans"][0].get("who") if role["spans"] else None
        ) or stem
        roles[str(name)] = role
    return roles


def _pick_reference(roles):
    """The reference role: the PS (most 'broadcast' spans wins — MSMW
    has several replicas), else the role with the most spans."""
    def score(item):
        name, r = item
        n_bcast = sum(1 for s in r["spans"] if s["phase"] == "broadcast")
        return (n_bcast, len(r["spans"]), name)

    # max with name as the last tie-break keeps the choice deterministic.
    name, _ = max(sorted(roles.items()), key=score)
    return name


def _phase_times(spans, phase, key="step"):
    """{step: (start, end)} for the FIRST span of ``phase`` per step."""
    out = {}
    for s in spans:
        st = s.get(key)
        if s["phase"] == phase and isinstance(st, int) and st not in out:
            out[st] = (s["t_wall"], s["t_wall"] + s["dur_s"])
    return out


def _recv_ends(spans):
    """{round: recv_end} — when this role finished receiving the
    round's model: latest_wait spans keyed by their harvested ``got``
    tag (SSMW workers), else model_gather/model_wait spans by step."""
    out = {}
    for s in spans:
        if s["phase"] == "latest_wait" and isinstance(s.get("got"), int):
            r = s["got"]
            end = s["t_wall"] + s["dur_s"]
            if r not in out or end < out[r]:
                out[r] = end
    if out:
        return out
    for phase in ("model_gather", "model_wait"):
        times = _phase_times(spans, phase)
        if times:
            return {r: e for r, (_, e) in times.items()}
    return out


def _fresh_rounds(roles, ref):
    """{worker_index: set(rounds)} where the ref's ``staleness`` events
    say the rank's frame was FRESH (staleness 0). The quorum-side upper
    anchor is only causally valid for fresh frames: under async reuse
    the PS can close round i's quorum on a worker's round i-k frame
    BEFORE that worker ever publishes round i. None when the run has no
    staleness events (synchronous: every consumed frame is fresh)."""
    out = {}
    seen = False
    for ev in roles[ref]["events"]:
        if ev.get("event") != "staleness":
            continue
        seen = True
        step = ev.get("step")
        for rank, tau in zip(ev.get("ranks") or (),
                             ev.get("staleness") or ()):
            if tau == 0 and isinstance(step, int):
                out.setdefault(int(rank), set()).add(step)
    return out if seen else None


def _align(roles, ref):
    """Per-role wall-clock offset (seconds to ADD to the role's clock)
    + the causal bracket that bounds it. Returns
    {role: {offset_s, lb_s, ub_s, anchors}}. The lower bound (cannot
    receive before the send began) is always valid; the upper bound
    (the PS closed the quorum after this worker's publish) holds only
    for rounds where the worker's frame entered FRESH, so async runs
    restrict it via the staleness events. An offset of 0 is preferred
    whenever the bracket admits it (co-located processes share a
    clock); otherwise the estimate is clamped into the bracket."""
    ref_spans = roles[ref]["spans"]
    bcast = _phase_times(ref_spans, "broadcast")
    quorum = _phase_times(ref_spans, "quorum")
    fresh = _fresh_rounds(roles, ref)
    out = {ref: {"offset_s": 0.0, "lb_s": None, "ub_s": None, "anchors": 0}}
    for name in sorted(roles):
        if name == ref:
            continue
        spans = roles[name]["spans"]
        recv = _recv_ends(spans)
        pub = _phase_times(spans, "publish")
        tail = name.rsplit("-", 1)[-1]
        widx = int(tail) if tail.isdigit() else None
        lbs, ubs = [], []
        for r, (b_start, _) in bcast.items():
            if r in recv:
                lbs.append(b_start - recv[r])
        for r, (_, q_end) in quorum.items():
            if r not in pub:
                continue
            if fresh is not None and widx is not None and \
                    r not in fresh.get(widx, ()):
                continue  # stale reuse: the quorum never waited on r
            ubs.append(q_end - pub[r][1])
        lb = statistics.median(lbs) if lbs else None
        ub = statistics.median(ubs) if ubs else None
        if lb is not None and ub is not None and lb <= ub:
            off = 0.0 if lb <= 0.0 <= ub else (lb + ub) / 2.0
        elif lb is not None:
            # No (valid) upper bound: clamp to the always-valid lower
            # bound, preferring the shared-clock hypothesis.
            off = 0.0 if lb <= 0.0 else lb
        elif ub is not None:
            off = 0.0 if ub >= 0.0 else ub
        else:
            off = 0.0
        out[name] = {
            "offset_s": off, "lb_s": lb, "ub_s": ub,
            "anchors": min(len(lbs), len(ubs)) or max(len(lbs), len(ubs)),
        }
    return out


def _main_tid(spans):
    """The role's main-loop thread: the tid owning the most
    step-tagged spans (waiter threads own the decode spans)."""
    counts = {}
    for s in spans:
        if isinstance(s.get("step"), int):
            counts[s.get("tid", 0)] = counts.get(s.get("tid", 0), 0) + 1
    if not counts:
        return 0
    return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[0][0]


def _outermost(spans):
    """Drop spans nested inside an earlier-kept span (same thread):
    the critical path must not double-count quorum AND the collect it
    wraps. Input must be sorted by start time."""
    kept, horizon = [], None
    for s in spans:
        start, end = s["t_wall"], s["t_wall"] + s["dur_s"]
        if horizon is not None and end <= horizon + 1e-9:
            continue  # fully inside the previous outermost span
        kept.append(s)
        horizon = end if horizon is None else max(horizon, end)
    return kept


def _critical_path(roles, ref):
    """Per-round attribution on the reference role's main thread:
    [{round, measured_s, attributed_s, residual_s, phases: {p: s}}].
    measured = start-to-start distance to the next round (the honest
    round time the phases must sum to); the last round uses its own
    span extent."""
    spans = [s for s in roles[ref]["spans"]
             if isinstance(s.get("step"), int)]
    tid = _main_tid(spans)
    spans = sorted(
        (s for s in spans if s.get("tid", 0) == tid),
        key=lambda s: (s["t_wall"], -s["dur_s"]),
    )
    by_round = {}
    for s in spans:
        by_round.setdefault(s["step"], []).append(s)
    # A "round" whose only activity is bare exchange spans (publish/
    # collect) is not a training round — e.g. the PS's stop-sentinel
    # publish at step num_iter. Keeping it would both add a phantom row
    # and stretch the previous round's start-to-start measurement over
    # the whole run tail (final eval, checkpoint close).
    role_phases = {"broadcast", "quorum", "gar_apply", "model_gather",
                   "dispatch", "eval", "checkpoint", "grad_compute",
                   "update", "gossip", "audit"}
    by_round = {
        r: ss for r, ss in by_round.items()
        if any(s["phase"] in role_phases for s in ss)
    }
    rounds_sorted = sorted(by_round)
    rows = []
    for idx, r in enumerate(rounds_sorted):
        outer = _outermost(by_round[r])
        start = min(s["t_wall"] for s in outer)
        end = max(s["t_wall"] + s["dur_s"] for s in outer)
        if idx + 1 < len(rounds_sorted):
            nxt = min(s["t_wall"] for s in by_round[rounds_sorted[idx + 1]])
            measured = nxt - start
        else:
            measured = end - start
        phases = {}
        for s in outer:
            phases[s["phase"]] = phases.get(s["phase"], 0.0) + s["dur_s"]
        attributed = sum(phases.values())
        rows.append({
            "round": r,
            "measured_s": round(measured, 6),
            "attributed_s": round(attributed, 6),
            "residual_s": round(measured - attributed, 6),
            "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
        })
    return rows


def _phase_digest(roles):
    """{role: {phase: {count, p50_s, p95_s, p99_s, total_s}}}."""
    out = {}
    for name in sorted(roles):
        durs = {}
        for s in roles[name]["spans"]:
            durs.setdefault(s["phase"], []).append(s["dur_s"])
        out[name] = {}
        for phase in sorted(durs):
            vals = sorted(durs[phase])
            out[name][phase] = {
                "count": len(vals),
                "p50_s": round(_percentile(vals, 50), 6),
                "p95_s": round(_percentile(vals, 95), 6),
                "p99_s": round(_percentile(vals, 99), 6),
                "total_s": round(sum(vals), 6),
            }
    return out


def _stragglers(roles, ref, offsets):
    """Per-worker publish lateness vs the reference round start, with
    the PS's suspicion score for the cross-check. Lateness for round i
    = (worker publish end, aligned) - (ref round broadcast start);
    the straggler is the rank whose median lateness tops the table.
    The cross-check prefers the WINDOWED (halflife-decayed) suspicion
    when the run recorded one (schema v7): a straggler is a live
    condition, and the cumulative score dilutes it with every clean
    round since — exactly the laundering a rotated Byzantine cohort
    exploits (DESIGN.md §16)."""
    bcast = _phase_times(roles[ref]["spans"], "broadcast")
    summary = roles[ref]["summary"] or {}
    suspicion = (
        summary.get("suspicion_decayed") or summary.get("suspicion") or []
    )
    rows = []
    workers = [n for n in sorted(roles) if "worker" in n]
    for name in workers:
        off = offsets.get(name, {}).get("offset_s", 0.0)
        pub = _phase_times(roles[name]["spans"], "publish")
        lates = [
            (pub[r][1] + off) - bcast[r][0]
            for r in pub if r in bcast
        ]
        if not lates:
            continue
        # worker index from the trailing -K of the role name when
        # present (cluster-worker-K), for the suspicion cross-check.
        widx = None
        tail = name.rsplit("-", 1)[-1]
        if tail.isdigit():
            widx = int(tail)
        rows.append({
            "role": name,
            "rounds": len(lates),
            "median_lateness_s": round(statistics.median(lates), 6),
            "p95_lateness_s": round(
                _percentile(sorted(lates), 95), 6
            ),
            "suspicion": (
                round(float(suspicion[widx]), 6)
                if widx is not None and widx < len(suspicion) else None
            ),
        })
    rows.sort(key=lambda r: (-r["median_lateness_s"], r["role"]))
    return rows


def _staleness(roles, ref):
    """Stale-frame reuse digest from the reference role's ``staleness``
    events (async runs; None on synchronous ones)."""
    reused = members = rounds_n = 0
    for ev in roles[ref]["events"]:
        if ev.get("event") == "staleness":
            rounds_n += 1
            members += len(ev.get("ranks") or ())
            reused += int(ev.get("reused") or 0)
    if not rounds_n:
        return None
    return {
        "rounds": rounds_n,
        "quorum_members": members,
        "reused_frames": reused,
        "reuse_rate": round(reused / members, 6) if members else 0.0,
    }


def build(paths, ref=None):
    """The full analysis dict every renderer consumes."""
    roles = load_run(paths)
    if not roles or all(not r["spans"] for r in roles.values()):
        raise SystemExit(
            "no span records found — run with --trace (or "
            "GARFIELD_TRACE=1) and --telemetry, then point the report "
            "at the run's telemetry directory"
        )
    ref = ref or _pick_reference(roles)
    if ref not in roles:
        raise SystemExit(
            f"reference role {ref!r} not in {sorted(roles)}"
        )
    offsets = _align(roles, ref)
    crit = _critical_path(roles, ref)
    align_err = max(
        (o["ub_s"] - o["lb_s"])
        for o in offsets.values()
        if o["lb_s"] is not None and o["ub_s"] is not None
    ) if len(offsets) > 1 and any(
        o["lb_s"] is not None and o["ub_s"] is not None
        for o in offsets.values()
    ) else 0.0
    return {
        "roles": roles,
        "ref": ref,
        "offsets": offsets,
        "alignment_error_s": round(max(align_err, 0.0), 6),
        "phases": _phase_digest(roles),
        "critical_path": crit,
        "stragglers": _stragglers(roles, ref, offsets),
        "staleness": _staleness(roles, ref),
    }


def chrome_trace(analysis):
    """Chrome trace-event JSON (the ``trace.json`` artifact): one
    process lane per role, thread lanes per recorded tid, microsecond
    timestamps relative to the earliest aligned span."""
    roles = analysis["roles"]
    offsets = analysis["offsets"]
    t0 = min(
        s["t_wall"] + offsets.get(name, {}).get("offset_s", 0.0)
        for name, r in roles.items() for s in r["spans"]
    )
    events = []
    for pid, name in enumerate(sorted(roles)):
        events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": name},
        })
        off = offsets.get(name, {}).get("offset_s", 0.0)
        for s in roles[name]["spans"]:
            args = {
                k: v for k, v in sorted(s.items())
                if k not in ("schema", "v", "kind", "phase", "t_wall",
                             "dur_s", "tid", "who")
            }
            events.append({
                "ph": "X", "pid": pid, "tid": int(s.get("tid", 0)),
                "name": s["phase"],
                "ts": int(round((s["t_wall"] + off - t0) * 1e6)),
                "dur": int(round(s["dur_s"] * 1e6)),
                "args": args,
            })
    # Stable order: metadata first per process, then by timestamp.
    events.sort(key=lambda e: (
        e["pid"], 0 if e["ph"] == "M" else 1, e.get("ts", 0),
        e.get("tid", 0), e["name"],
    ))
    return {"displayTimeUnit": "ms", "traceEvents": events}


def _ms(v):
    return "-" if v is None else f"{v * 1e3:.3f}"


def render_markdown(analysis):
    """The run report (``report.md``): deterministic for a fixed run."""
    roles = analysis["roles"]
    ref = analysis["ref"]
    lines = ["# Garfield run report (distributed round tracing)", ""]
    lines.append(
        f"Roles: {', '.join(sorted(roles))} — reference: **{ref}**."
    )
    lines.append(
        f"Clock-alignment error bound: "
        f"{_ms(analysis['alignment_error_s'])} ms "
        "(causal round-anchor bracket width; cross-process claims "
        "tighter than this are not supported by the data)."
    )
    lines.append("")
    lines.append("## Clock offsets (round-tag anchors)")
    lines.append("")
    lines.append("| role | offset (ms) | bracket lo | bracket hi | anchors |")
    lines.append("|---|---|---|---|---|")
    for name in sorted(analysis["offsets"]):
        o = analysis["offsets"][name]
        lines.append(
            f"| {name} | {_ms(o['offset_s'])} | {_ms(o['lb_s'])} "
            f"| {_ms(o['ub_s'])} | {o['anchors']} |"
        )
    lines.append("")
    lines.append("## Per-phase latency (ms)")
    for name in sorted(analysis["phases"]):
        lines.append("")
        lines.append(f"### {name}")
        lines.append("")
        lines.append("| phase | count | p50 | p95 | p99 | total |")
        lines.append("|---|---|---|---|---|---|")
        for phase, st in analysis["phases"][name].items():
            lines.append(
                f"| {phase} | {st['count']} | {_ms(st['p50_s'])} "
                f"| {_ms(st['p95_s'])} | {_ms(st['p99_s'])} "
                f"| {_ms(st['total_s'])} |"
            )
    crit = analysis["critical_path"]
    lines.append("")
    lines.append(f"## Per-round critical path ({ref})")
    lines.append("")
    if crit:
        phases = sorted({p for row in crit for p in row["phases"]})
        total_meas = sum(r["measured_s"] for r in crit)
        total_attr = sum(r["attributed_s"] for r in crit)
        lines.append(
            f"{len(crit)} rounds, {total_meas * 1e3:.3f} ms measured, "
            f"{total_attr * 1e3:.3f} ms attributed to phases "
            f"({100.0 * total_attr / total_meas:.1f}% — the residual is "
            "untraced host glue between spans)."
        )
        lines.append("")
        header = "| round | measured | " + " | ".join(phases) + \
            " | residual |"
        lines.append(header)
        lines.append("|---" * (len(phases) + 3) + "|")
        for row in crit:
            cells = [_ms(row["phases"].get(p, 0.0)) for p in phases]
            lines.append(
                f"| {row['round']} | {_ms(row['measured_s'])} | "
                + " | ".join(cells)
                + f" | {_ms(row['residual_s'])} |"
            )
        # Aggregate attribution: where does a round's wall clock GO?
        lines.append("")
        lines.append("| phase | total (ms) | share of measured |")
        lines.append("|---|---|---|")
        for p in phases:
            tot = sum(r["phases"].get(p, 0.0) for r in crit)
            lines.append(
                f"| {p} | {_ms(tot)} | "
                f"{100.0 * tot / total_meas:.1f}% |"
            )
        resid = total_meas - total_attr
        lines.append(
            f"| (residual) | {_ms(resid)} | "
            f"{100.0 * resid / total_meas:.1f}% |"
        )
    else:
        lines.append("No round-tagged spans on the reference role.")
    lines.append("")
    lines.append("## Straggler ranking (publish lateness vs suspicion)")
    lines.append("")
    if analysis["stragglers"]:
        lines.append(
            "| role | rounds | median lateness (ms) | p95 (ms) "
            "| suspicion |"
        )
        lines.append("|---|---|---|---|---|")
        for row in analysis["stragglers"]:
            susp = "-" if row["suspicion"] is None else \
                f"{row['suspicion']:.4f}"
            lines.append(
                f"| {row['role']} | {row['rounds']} "
                f"| {_ms(row['median_lateness_s'])} "
                f"| {_ms(row['p95_lateness_s'])} | {susp} |"
            )
    else:
        lines.append("No worker publish spans found.")
    lines.append("")
    lines.append("## Stale-frame reuse (async plane)")
    lines.append("")
    st = analysis["staleness"]
    if st is None:
        lines.append("Synchronous run — no staleness events.")
    else:
        lines.append(
            f"{st['rounds']} async rounds, {st['quorum_members']} quorum "
            f"members, {st['reused_frames']} reused stale frames "
            f"(reuse rate {100.0 * st['reuse_rate']:.1f}%)."
        )
    lines.append("")
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Merge a traced run's per-role telemetry JSONL into "
                    "a Chrome trace + markdown run report "
                    "(docs/TELEMETRY.md §4)."
    )
    p.add_argument("run", nargs="+",
                   help="telemetry directory of the run (or explicit "
                        ".jsonl files)")
    p.add_argument("--ref", default=None,
                   help="reference role for alignment/critical path "
                        "(default: the PS — most broadcast spans)")
    p.add_argument("--trace-out", default=None,
                   help="Chrome trace JSON path (default: "
                        "<dir>/trace.json)")
    p.add_argument("--md-out", default=None,
                   help="markdown report path (default: <dir>/report.md)")
    args = p.parse_args(argv)
    src = args.run[0] if len(args.run) == 1 else list(args.run)
    out_dir = src if isinstance(src, str) and os.path.isdir(src) else \
        os.path.dirname(args.run[0]) or "."
    analysis = build(src, ref=args.ref)
    trace_path = args.trace_out or os.path.join(out_dir, "trace.json")
    md_path = args.md_out or os.path.join(out_dir, "report.md")
    with open(trace_path, "w") as fp:
        json.dump(chrome_trace(analysis), fp, sort_keys=True,
                  separators=(",", ":"))
        fp.write("\n")
    md = render_markdown(analysis)
    with open(md_path, "w") as fp:
        fp.write(md)
    print(md)
    print(f"[report] chrome trace: {trace_path}  (open in Perfetto / "
          "chrome://tracing)", file=sys.stderr)
    print(f"[report] markdown: {md_path}", file=sys.stderr)
    return analysis


if __name__ == "__main__":
    main(sys.argv[1:])

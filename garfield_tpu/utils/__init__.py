"""Shared infrastructure: logging, registries, selectors, timing.

TPU-native counterpart of the reference's two tool packages:
  - pytorch_impl/libs/tools/ (colored Context logging :34-122, ClassRegister
    misc.py:118-172, pairwise misc.py:518-530, timing misc.py:533-568)
  - pytorch_impl/libs/garfieldpp/tools.py (select_loss :47-57,
    select_optimizer :107-123, bandwidth accounting :152-163)
"""

from .tools import (  # noqa: F401
    Context,
    ClassRegister,
    fatal,
    info,
    pairwise,
    trace,
    warning,
)
from .selectors import (  # noqa: F401
    select_loss,
    select_optimizer,
    adjust_learning_rate,
)

"""Hand-crafted small nets (counterpart of garfieldpp/models/nets.py).

``Net`` (the "convnet" MNIST model, nets.py:59-77), ``Cifarnet``
(nets.py:40-57) and ``CNNet`` (nets.py:79-135) with identical layer graphs,
in NHWC flax.
"""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import max_pool, norm


class Net(nn.Module):
    """MNIST convnet (nets.py:59-77): conv5x5(10) -> pool -> conv5x5(20) +
    dropout2d -> pool -> fc50 -> dropout -> fc -> log_softmax."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(max_pool(x, 2))
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        # torch Dropout2d zeroes whole channels (p=0.5 default).
        x = nn.Dropout(0.5, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = nn.relu(max_pool(x, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return nn.log_softmax(x)


class Cifarnet(nn.Module):
    """CIFAR-10 LeNet-style net (nets.py:40-57)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = max_pool(nn.relu(nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype)(x)), 2)
        x = max_pool(nn.relu(nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)), 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class CNNet(nn.Module):
    """Three conv blocks + 3-layer head (nets.py:79-135)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        for block, feats in enumerate((32, 128, 256)):
            x = nn.Conv(feats, (3, 3), padding=1, use_bias=True, dtype=self.dtype)(x)
            x = nn.relu(norm(train, dtype=self.dtype)(x))
            x = nn.Conv(feats * 2 if block == 0 else feats, (3, 3), padding=1,
                        use_bias=True, dtype=self.dtype)(x)
            x = max_pool(nn.relu(x), 2)
            if block == 1:
                x = nn.Dropout(0.05, broadcast_dims=(1, 2), deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.1, deterministic=not train)(x)
        x = nn.relu(nn.Dense(1024, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.Dropout(0.1, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)

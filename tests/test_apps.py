"""End-to-end CLI tests for the application layer (SURVEY §4: replaces the
reference's run-it-and-see with real integration tests; the LEARN demo's
multi-process-on-localhost harness, demo.py:264-320, becomes plain function
calls on the virtual 8-device mesh from conftest).

The full-training smokes are ``slow``-marked (same tier convention as
test_cluster/test_demo): each is a ~1-minute CPU training run, and a dozen
of them blow the tier-1 wall-clock budget on a 1-core container while
re-covering flows the unit files (test_parallel, test_fold,
test_entry_resilience) already pin piecewise. Tier-1 keeps the
checkpoint/resume roundtrip and the cheap validation tests; run the whole
file without ``-m 'not slow'`` for the full sweep."""

import json
import os

import pytest

from garfield_tpu.apps import (
    aggregathor as app_aggregathor,
    byzsgd as app_byzsgd,
    centralized as app_centralized,
    garfield_cc as app_garfield_cc,
    learn as app_learn,
)

FAST = [
    "--dataset", "mnist", "--model", "convnet", "--loss", "nll",
    "--batch", "8", "--num_iter", "3", "--train_size", "256",
    "--acc_freq", "2",
]


@pytest.mark.slow
def test_centralized_runs():
    state, summary = app_centralized.main(FAST)
    assert summary["final_accuracy"] >= 0.0
    assert int(state.step) == 3


@pytest.mark.slow
def test_aggregathor_krum_lie():
    state, summary = app_aggregathor.main(
        FAST + ["--num_workers", "8", "--fw", "2", "--gar", "krum",
                "--attack", "lie"]
    )
    assert int(state.step) == 3


@pytest.mark.slow
def test_async_eval_matches_sync(capsys):
    """Overlapped accuracy (the default, mirroring the reference's side
    thread at Aggregathor/trainer.py:251-264) must report the same values
    as the inline --sync_eval path, and all reports must flush before the
    summary line."""
    flags = FAST + ["--num_workers", "8", "--gar", "average"]
    outs = []
    for mode in ([], ["--sync_eval"]):
        app_aggregathor.main(flags + mode)
        lines = capsys.readouterr().out.splitlines()
        # Strip the wall-clock suffix: only epoch + accuracy must match.
        accs = [l.split(" Time:")[0] for l in lines if l.startswith("Epoch:")]
        summary_idx = max(
            i for i, l in enumerate(lines) if l.startswith("Epoch:")
        )
        assert any(l.startswith('{"tag"') for l in lines[summary_idx:])
        outs.append(accs)
    assert outs[0] == outs[1]
    assert len(outs[0]) >= 2  # acc_freq=2 over 3 iters -> evals at 0 and 2


@pytest.mark.slow
def test_aggregathor_subset_and_layer_granularity():
    _, summary = app_aggregathor.main(
        FAST + ["--num_workers", "8", "--fw", "1", "--gar", "median",
                "--subset", "6", "--granularity", "layer"]
    )
    assert summary["final_loss"] is not None


@pytest.mark.slow
def test_byzsgd_with_byz_ps():
    state, _ = app_byzsgd.main(
        FAST + ["--num_workers", "8", "--num_ps", "4", "--fw", "1",
                "--fps", "1", "--gar", "median", "--attack", "reverse",
                "--ps_attack", "random", "--mesh", "ps=2,workers=4"]
    )
    assert int(state.step) == 3


@pytest.mark.slow
def test_learn_non_iid():
    state, _ = app_learn.main(
        FAST + ["--num_workers", "8", "--fw", "1", "--gar", "median",
                "--non_iid"]
    )
    assert int(state.step) == 3


@pytest.mark.slow
def test_pima_ragged_test_set_evalset():
    """pima's 168-sample test set batches into (100, 68) — EvalSet must
    handle the ragged tail the app loop now always wraps (regression: the
    first EvalSet stacked blindly and died at startup on pima)."""
    state, summary = app_learn.main([
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--num_iter", "3", "--acc_freq", "2",
        "--num_workers", "8", "--fw", "1", "--gar", "median",
    ])
    assert int(state.step) == 3
    assert 0.0 <= summary["final_accuracy"] <= 1.0


@pytest.mark.slow
def test_garfield_cc_modes():
    for mode in ("vanilla", "aggregathor"):
        _, summary = app_garfield_cc.main(
            FAST + ["--mode", mode, "--num_workers", "8", "--fw", "1",
                    "--gar", "median"]
        )
        assert summary["final_loss"] is not None


@pytest.mark.slow
def test_garfield_cc_guanyu_layer_granularity():
    state, summary = app_garfield_cc.main(
        FAST + ["--mode", "guanyu", "--num_workers", "4", "--num_ps", "2",
                "--fw", "1", "--fps", "0", "--gar", "median",
                "--mesh", "ps=2,workers=4"]
    )
    assert int(state.step) == 3 and summary["final_loss"] is not None


# Two full app runs + a resume — the single heaviest test in the suite;
# off the tier-1 fast shard for wall-time budget. Resume semantics stay
# tier-1-covered by test_federated's TestFailoverDeterminism.
@pytest.mark.slow
def test_checkpoint_resume(tmp_path):
    ckpt_args = FAST + [
        "--num_workers", "8", "--gar", "average",
        "--checkpoint_dir", str(tmp_path / "ckpt"), "--checkpoint_freq", "2",
    ]
    state1, _ = app_aggregathor.main(ckpt_args)
    # Resume continues from the persisted step, not from scratch.
    state2, _ = app_aggregathor.main(
        [a if a != "3" else "5" for a in ckpt_args] + ["--resume"]
    )
    assert int(state2.step) == 5


@pytest.mark.slow
def test_fault_crash_schedule():
    """--fault_crashes: host 3 dies at step 2; the run re-jits the step with
    that slot as a zero-gradient Byzantine row and still converges on the
    remaining honest workers (SURVEY §5 failure simulation; the reference's
    mar='crash', Garfield_CC/trainer.py:97,137)."""
    state, summary = app_aggregathor.main(
        FAST + ["--num_workers", "8", "--fw", "2", "--gar", "median",
                "--num_iter", "5",
                "--fault_crashes", json.dumps({"3": 2})]
    )
    assert int(state.step) == 5
    assert summary["final_loss"] is not None
    import numpy as np

    assert np.isfinite(summary["final_loss"])


def test_fault_crashes_rejects_attack_combo():
    with pytest.raises(SystemExit):
        app_aggregathor.main(
            FAST + ["--num_workers", "8", "--fw", "2", "--gar", "median",
                    "--attack", "lie",
                    "--fault_crashes", json.dumps({"0": 1})]
        )


def test_fault_crashes_validates_budget_and_layout():
    base = FAST + ["--num_workers", "8", "--gar", "median", "--num_iter", "5"]
    with pytest.raises(SystemExit):  # 3 dead slots > fw=2
        app_aggregathor.main(
            base + ["--fw", "2",
                    "--fault_crashes", json.dumps({"0": 0, "1": 0, "2": 0})]
        )
    with pytest.raises(SystemExit):  # hosts don't divide slots
        app_aggregathor.main(
            base + ["--fw", "2", "--fault_hosts", "3",
                    "--fault_crashes", json.dumps({"0": 0})]
        )
    with pytest.raises(SystemExit):  # host id out of range
        app_aggregathor.main(
            base + ["--fw", "2", "--fault_crashes", json.dumps({"9": 0})]
        )


@pytest.mark.slow
def test_fault_crash_learn_model_gossip():
    """In LEARN, a crashed node must not gossip its (honest) model either:
    the fault wiring sets the model-space crash attack alongside the
    gradient one."""
    state, summary = app_learn.main(
        FAST + ["--num_workers", "8", "--fw", "2", "--gar", "median",
                "--num_iter", "4",
                "--fault_crashes", json.dumps({"2": 1})]
    )
    assert int(state.step) == 4
    import numpy as np

    assert np.isfinite(summary["final_loss"])


@pytest.mark.slow
def test_bench_driver_artifact_smoke():
    """bench.py is the driver's official perf artifact (BENCH_r02 was lost
    to an unhandled transient once — VERDICT r2 #1): it must run end to end
    and print exactly one valid JSON line on stdout whatever the knobs.
    Tiny config on the CPU backend; the off-default knobs must also report
    vs_baseline null (not a krum-vs-average apples-to-oranges ratio)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # stay off the TPU tunnel
    env.update(
        JAX_PLATFORMS="cpu",
        GARFIELD_BENCH_STEPS="2",
        GARFIELD_BENCH_TRIALS="1",
        GARFIELD_BENCH_WORKERS="4",
        GARFIELD_BENCH_F="1",
        GARFIELD_BENCH_GAR="median",
        GARFIELD_BENCH_ATTACK="lie",
        GARFIELD_BENCH_BATCH="2",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        cwd=repo, env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["value"] > 0
    assert out["unit"] == "steps/s/chip"
    assert out["metric"].endswith("w4_f1_median_lie")
    assert out["vs_baseline"] is None  # off-default config: no ratchet ratio
    assert out["chunk_steps"] == 1  # attribution field (BENCH_r06+ rows)


# Cheap end-to-end config for the chunked-loop tests: pimanet compiles in
# seconds where the mnist convnet costs ~1 min/run on the 1-core container.
PIMA_FAST = [
    "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
    "--batch", "8", "--acc_freq", "3", "--num_workers", "8",
    "--gar", "median",
]


def _params_equal(a, b):
    import jax
    import numpy as np

    for la, lb in zip(
        jax.tree.leaves(jax.device_get(a.params)),
        jax.tree.leaves(jax.device_get(b.params)),
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_chunked_checkpoint_resume_matches_unchunked(tmp_path):
    """Mid-chunk checkpoint/resume: --chunk_steps 3 with a non-aligned
    checkpoint cadence 2 clips chunks at every save, a 'killed' run
    (shorter --num_iter) resumes from the persisted step, and the final
    params are bitwise the unchunked full run's."""
    ref, _ = app_aggregathor.main(PIMA_FAST + ["--num_iter", "5"])
    ck = ["--checkpoint_dir", str(tmp_path / "ck"), "--checkpoint_freq",
          "2", "--chunk_steps", "3"]
    killed, _ = app_aggregathor.main(PIMA_FAST + ["--num_iter", "3"] + ck)
    assert int(killed.step) == 3
    resumed, _ = app_aggregathor.main(
        PIMA_FAST + ["--num_iter", "5", "--resume"] + ck
    )
    assert int(resumed.step) == 5
    _params_equal(ref, resumed)


def test_chunked_telemetry_fans_out_per_step_records(tmp_path):
    """K steps per dispatch must still land K per-step records in the
    hub: the JSONL has one 'step' record per training step, in order,
    and the artifact validates against the schema."""
    tel = str(tmp_path / "tel")
    app_aggregathor.main(
        PIMA_FAST + ["--num_iter", "5", "--chunk_steps", "4",
                     "--attack", "lie", "--fw", "2", "--gar", "krum",
                     "--telemetry", tel]
    )
    from garfield_tpu.telemetry.exporters import validate_jsonl

    path = os.path.join(tel, "telemetry.jsonl")
    assert validate_jsonl(path) >= 7  # run + 5 steps + summary
    recs = [json.loads(l) for l in open(path)]
    assert [r["step"] for r in recs if r["kind"] == "step"] == list(range(5))


def test_resume_build_gets_remaining_num_iter(tmp_path, monkeypatch):
    """The run-length hint (core.slot_path_decision's unroll-amortization
    input) must be the REMAINING steps on a resumed/re-jit build, not the
    original total — a resumed program only serves what is left."""
    import functools

    from garfield_tpu.parallel import aggregathor as topo

    seen = []
    real = topo.make_trainer

    @functools.wraps(real)
    def spy(*a, **kw):
        seen.append(kw.get("num_iter"))
        return real(*a, **kw)

    monkeypatch.setattr(topo, "make_trainer", spy)
    ck = ["--checkpoint_dir", str(tmp_path / "ck"), "--checkpoint_freq", "2"]
    app_aggregathor.main(PIMA_FAST + ["--num_iter", "2"] + ck)
    assert seen == [2]
    seen.clear()
    app_aggregathor.main(
        PIMA_FAST + ["--num_iter", "6", "--resume", "--chunk_steps", "2"]
        + ck
    )
    assert seen == [4]  # 6 total - 2 already served


@pytest.mark.slow
def test_chunked_crash_boundary_matches_unchunked():
    """A --fault_crashes event must clip the chunk and re-jit exactly as
    the per-step loop does: the chunked trajectory across the crash is
    bitwise the unchunked one."""
    flags = PIMA_FAST + ["--fw", "2", "--num_iter", "5",
                         "--fault_crashes", json.dumps({"3": 2})]
    ref, _ = app_aggregathor.main(flags)
    chunked, _ = app_aggregathor.main(flags + ["--chunk_steps", "4"])
    assert int(chunked.step) == 5
    _params_equal(ref, chunked)


@pytest.mark.slow
def test_chunked_checkpoint_resume_full_variant(tmp_path):
    """The issue-spec numbers on the real smoke config: convnet/mnist,
    --chunk_steps 4 against checkpoint cadence 6 (non-aligned), killed
    mid-stride at step 7 and resumed to 8 — final params bitwise equal to
    the unchunked straight-through run."""
    common = FAST + ["--num_workers", "8", "--gar", "median"]
    base = common + ["--num_iter", "8"]  # last --num_iter wins
    ref, _ = app_aggregathor.main(base)
    ck = ["--checkpoint_dir", str(tmp_path / "ck"), "--checkpoint_freq",
          "6", "--chunk_steps", "4"]
    killed, _ = app_aggregathor.main(common + ["--num_iter", "7"] + ck)
    assert int(killed.step) == 7
    resumed, _ = app_aggregathor.main(base + ["--resume"] + ck)
    assert int(resumed.step) == 8
    _params_equal(ref, resumed)


def test_cluster_host_attack_cohort_math():
    """The cluster attacker's lie/empire statistics must match the
    reference formulas (byzWorker.py:108-143) on a known cohort stack."""
    import numpy as np

    from garfield_tpu.apps.cluster import _host_attack

    stack = np.asarray(
        [[1.0, 2.0, 3.0], [3.0, 6.0, 1.0]], dtype=np.float32
    )
    kind, fn, cohort = _host_attack("lie", {}, fw=2)
    assert (kind, cohort) == ("cohort", 2)
    mu = stack.mean(0)
    sigma = stack.std(0, ddof=1)
    np.testing.assert_allclose(fn(stack), mu + 1.035 * sigma, rtol=1e-6)

    kind, fn, cohort = _host_attack("empire", {"eps": 4.0, "cohort": 3}, fw=2)
    assert (kind, cohort) == ("cohort", 3)
    np.testing.assert_allclose(fn(stack), -4.0 * mu, rtol=1e-6)

    # fw=1 cohort: Bessel sigma is NaN, like torch.std of one sample.
    kind, fn, cohort = _host_attack("lie", {}, fw=1)
    out = fn(stack[:1])
    assert np.isnan(out).all()

    kind, fn, _ = _host_attack("reverse", {}, fw=1)
    assert kind == "post"
    np.testing.assert_allclose(fn(stack[0]), -100.0 * stack[0])

    with pytest.raises(SystemExit):
        _host_attack("unknown-attack", {}, fw=1)

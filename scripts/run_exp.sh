#!/usr/bin/env bash
# Multi-host experiment fan-out.
#
# Counterpart of the reference's per-app run_exp.sh (ssh loops over
# `servers`/`workers` host files, Aggregathor/run_exp.sh:41-60). One host =
# one JAX process (multi-controller); the coordinator is the first host in
# the hosts file, mirroring the reference's rank-0 --master convention.
#
# Usage:
#   scripts/run_exp.sh <hosts_file> <app> [app args...]
# e.g.
#   scripts/run_exp.sh nodes aggregathor --dataset cifar10 --model resnet18 \
#       --num_workers 8 --fw 2 --gar krum --attack lie
#
# Each line of <hosts_file> is "host[:port]". Requires passwordless ssh and
# this repo at the same path on every host (Grid5000/vagrant style,
# pytorch_impl/README.md:63-67).
set -euo pipefail

HOSTS_FILE=${1:?hosts file}
APP=${2:?app name (centralized|aggregathor|byzsgd|learn|garfield_cc)}
shift 2

mapfile -t HOSTS < <(grep -v '^#' "$HOSTS_FILE" | sed '/^$/d')
NUM=${#HOSTS[@]}
COORD=${HOSTS[0]}
[[ "$COORD" == *:* ]] || COORD="$COORD:9900"
REPO_DIR=$(cd "$(dirname "$0")/.." && pwd)

# Shell-quote the app args so JSON/space-containing values (--opt_args
# '{"lr":"0.2"}') survive the remote shell's word splitting.
APP_ARGS=""
for arg in "$@"; do
  APP_ARGS+=$(printf ' %q' "$arg")
done

echo "launching $APP on $NUM hosts (coordinator $COORD)"
for i in "${!HOSTS[@]}"; do
  HOST=${HOSTS[$i]%%:*}
  CONFIG=$(python3 - "$i" "$NUM" "$COORD" <<'PY'
import json, sys
i, num, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
print(json.dumps({
    "cluster": {"worker": [coord] + [f"host{k}" for k in range(1, num)]},
    "task": {"type": "worker", "index": i},
}))
PY
)
  ssh -o StrictHostKeyChecking=no "$HOST" \
    "cd '$REPO_DIR' && GARFIELD_CONFIG='$CONFIG' \
     nohup python3 -m garfield_tpu.apps.$APP$APP_ARGS \
     > run_${APP}_rank${i}.log 2>&1 &" &
done
wait
echo "all ranks launched; logs: run_${APP}_rank*.log on each host"

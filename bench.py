"""North-star benchmark: Byzantine-resilient SGD steps/sec/chip.

Config (BASELINE.md measurement plan, mirroring Aggregathor/run_exp.sh:5-14):
ResNet-18 / CIFAR-10, 8 logical workers folded onto the available chip(s),
batch 25/worker, Multi-Krum with f=2 under the "little is enough" lie attack
(byzWorker.py:108-125) — i.e. the full hot path: per-worker fwd+bwd,
all_gather, on-device attack injection, O(n^2 d) Krum scoring, SGD update,
all inside one jit'd SPMD program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` divides by BASELINE.json's measured reference number when one
exists; the reference repo publishes none (SURVEY §6), so it defaults to 1.0.

Env knobs: GARFIELD_BENCH_STEPS (timed steps, default 20),
GARFIELD_BENCH_WORKERS, GARFIELD_BENCH_F, GARFIELD_BENCH_BATCH.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    import optax

    from garfield_tpu import models
    from garfield_tpu.parallel import aggregathor, mesh as mesh_lib
    from garfield_tpu.utils import profiling, selectors

    num_workers = int(os.environ.get("GARFIELD_BENCH_WORKERS", 8))
    f = int(os.environ.get("GARFIELD_BENCH_F", 2))
    batch = int(os.environ.get("GARFIELD_BENCH_BATCH", 25))
    steps = max(1, int(os.environ.get("GARFIELD_BENCH_STEPS", 20)))

    platform = jax.devices()[0].platform
    # bf16 compute routes conv/matmul onto the MXU; params stay f32.
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    module = models.select_model("resnet18", "cifar10", dtype=dtype)
    loss_fn = selectors.select_loss("cross-entropy")
    # Reference AggregaThor defaults: SGD lr 0.2, momentum 0.9, wd 5e-4
    # (Aggregathor/run_exp.sh:39-40).
    opt = selectors.select_optimizer(
        "sgd", lr=0.2, momentum=0.9, weight_decay=5e-4
    )

    n_dev = len(jax.devices())
    axis_size = n_dev if num_workers % n_dev == 0 else 1
    mesh = mesh_lib.make_mesh(
        {"workers": axis_size}, devices=jax.devices()[:axis_size]
    )
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss_fn, opt, "krum",
        num_workers=num_workers, f=f, attack="lie", mesh=mesh,
    )

    rng = np.random.default_rng(1234)
    x = jnp.asarray(
        rng.standard_normal((num_workers, batch, 32, 32, 3)), jnp.float32
    )
    y = jnp.asarray(rng.integers(0, 10, (num_workers, batch)), jnp.int32)
    state = init_fn(jax.random.PRNGKey(1234), x[0])

    for _ in range(3):  # warmup: compile + stabilize clocks
        state, metrics = step_fn(state, x, y)
    float(metrics["loss"])  # host readback: drains the queue (on tunneled
    # backends block_until_ready can return before the device finishes; a
    # readback is the only reliable sync, at a constant queue-flush cost)

    state_box = [state]

    def timed(k):
        state = state_box[0]
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = step_fn(state, x, y)
        float(metrics["loss"])
        state_box[0] = state
        return time.perf_counter() - t0

    # Paired-reps timing: the constant sync cost cancels in the difference
    # (utils/profiling.paired_reps; see PERF.md "Timing methodology").
    dt = profiling.paired_reps(timed, steps)

    steps_per_sec_per_chip = 1.0 / dt / axis_size
    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as fp:
            baseline = json.load(fp).get("published", {}).get(
                "steps_per_sec_per_chip"
            )
    except OSError:
        pass
    vs = steps_per_sec_per_chip / baseline if baseline else 1.0
    print(json.dumps({
        "metric": "byzsgd_steps_per_sec_per_chip_resnet18_cifar10_w8_f2_krum_lie",
        "value": round(steps_per_sec_per_chip, 4),
        "unit": "steps/s/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()

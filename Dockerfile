# Counterpart of pytorch_impl/Dockerfile: the reference image warm-builds
# the native modules and runs the demo once ("build success => tests pass",
# .github/workflows/build.yml:12-45 + Dockerfile:12). This image instead
# installs the package, JIT-builds the C++ runtime, and runs the real test
# suite on a virtual 8-device CPU mesh — the fake-backend the reference
# lacked (SURVEY §4).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY garfield_tpu ./garfield_tpu
COPY tests ./tests
COPY bench.py __graft_entry__.py ./

RUN pip install --no-cache-dir "jax[cpu]" flax optax orbax-checkpoint \
        chex einops pytest && \
    pip install --no-cache-dir -e .

# Warm-build the native C++ GAR kernels + multibuffer (import triggers the
# content-hashed g++ JIT build, native/__init__.py) and run the suite.
RUN python -c "import garfield_tpu.native as n; print('native:', n.available())" && \
    python -m pytest tests/ -q

# Default command: the browser demo (LEARN on Pima), like the reference's
# deployed demonstrator (LEARN/demo.py + scripts/deploy.sh).
EXPOSE 8000
CMD ["python", "-m", "garfield_tpu.apps.demo", "--port", "8000"]
